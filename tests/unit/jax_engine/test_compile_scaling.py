"""Program-size flatness gate (docs/internals/compile-pathology.md).

The round-3 TPU compile blow-up scaled with the vmap batch width while the
program itself was shape-flat.  These tests pin the flatness: the jitted
fast-path program (jaxpr equation count and StableHLO size) must be
IDENTICAL across vmap widths and scan lengths, so any future edit that
makes the program grow with chunk fails here, on CPU, before it can wedge
a TPU worker.  The metric is computed by the same helper the measurement
script uses (``asyncflow_tpu.utils.program_size``).
"""

from __future__ import annotations

import os
import sys

import pytest

from asyncflow_tpu.compiler.plan import compile_payload
from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine
from asyncflow_tpu.utils.program_size import scanned_program_size

_SCRIPTS = os.path.join(
    os.path.dirname(__file__), "..", "..", "..", "scripts",
)


@pytest.fixture(scope="module")
def fast_engine() -> FastEngine:
    sys.path.insert(0, _SCRIPTS)
    try:
        from _common import load_example_payload
    finally:
        sys.path.remove(_SCRIPTS)
    # small horizon keeps the trace fast; program *structure* is
    # horizon-independent, which is exactly what these tests pin
    plan = compile_payload(load_example_payload(30))
    assert plan.fastpath_ok, plan.fastpath_reason
    return FastEngine(plan)


def test_program_flat_in_vmap_width(fast_engine: FastEngine) -> None:
    small = scanned_program_size(fast_engine, inner=2, blocks=1)
    wide = scanned_program_size(fast_engine, inner=16, blocks=1)
    assert small == wide


def test_program_flat_in_scan_length(fast_engine: FastEngine) -> None:
    short = scanned_program_size(fast_engine, inner=4, blocks=2)
    long = scanned_program_size(fast_engine, inner=4, blocks=16)
    assert short == long
