"""Property tests: batched-engine invariants on randomized payloads.

The invariants the reference states as comments
(`/root/reference/src/asyncflow/runtime/actors/server.py:186-193`: queue
lengths never negative, RAM within [0, capacity]) plus conservation
(generated = completed + dropped + overflow + in-flight at the horizon),
checked on the JAX engines across randomized topologies/workloads rather
than hand-picked examples.
"""

from __future__ import annotations

import numpy as np
import pytest

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.engines.jaxsim.engine import Engine, scenario_keys
from asyncflow_tpu.schemas.payload import SimulationPayload


def _random_payload(rng: np.random.Generator) -> SimulationPayload:
    """A random small scenario: 1-3 servers, optional LB, random endpoints."""
    n_servers = int(rng.integers(1, 4))
    use_lb = bool(rng.integers(0, 2)) and n_servers >= 2

    def endpoint(i: int) -> dict:
        steps = []
        for _ in range(int(rng.integers(1, 4))):
            kind = rng.choice(["cpu", "io", "ram"])
            if kind == "cpu":
                steps.append(
                    {
                        "kind": "cpu_bound_operation",
                        "step_operation": {"cpu_time": float(rng.uniform(0.001, 0.01))},
                    },
                )
            elif kind == "io":
                steps.append(
                    {
                        "kind": "io_wait",
                        "step_operation": {
                            "io_waiting_time": float(rng.uniform(0.002, 0.03)),
                        },
                    },
                )
            else:
                steps.append(
                    {
                        "kind": "ram",
                        "step_operation": {"necessary_ram": int(rng.integers(32, 256))},
                    },
                )
        if not any("cpu_time" in s["step_operation"] or "io_waiting_time" in s["step_operation"] for s in steps):
            steps.append(
                {"kind": "io_wait", "step_operation": {"io_waiting_time": 0.005}},
            )
        return {"endpoint_name": f"ep-{i}", "steps": steps}

    servers = [
        {
            "id": f"srv-{i}",
            "server_resources": {
                "cpu_cores": int(rng.integers(1, 3)),
                "ram_mb": int(rng.integers(512, 4096)),
            },
            "endpoints": [endpoint(j) for j in range(int(rng.integers(1, 3)))],
        }
        for i in range(n_servers)
    ]

    edges = [
        {
            "id": "gen-client",
            "source": "rqs-1",
            "target": "client-1",
            "latency": {"mean": 0.003, "distribution": "exponential"},
            "dropout_rate": float(rng.uniform(0, 0.05)),
        },
    ]
    if use_lb:
        covered = [s["id"] for s in servers[:2]]
        edges.append(
            {
                "id": "client-lb",
                "source": "client-1",
                "target": "lb-1",
                "latency": {"mean": 0.002, "distribution": "exponential"},
            },
        )
        edges += [
            {
                "id": f"lb-{sid}",
                "source": "lb-1",
                "target": sid,
                "latency": {"mean": 0.002, "distribution": "exponential"},
            }
            for sid in covered
        ]
        chain = covered
    else:
        edges.append(
            {
                "id": "client-srv",
                "source": "client-1",
                "target": "srv-0",
                "latency": {"mean": 0.002, "distribution": "exponential"},
            },
        )
        chain = ["srv-0"]
    # remaining servers become a chain behind the first one
    rest = [s["id"] for s in servers if s["id"] not in chain]
    hops = [chain[0], *rest] if not use_lb else rest
    if use_lb:
        for sid in chain:
            edges.append(
                {
                    "id": f"{sid}-out",
                    "source": sid,
                    "target": rest[0] if rest else "client-1",
                    "latency": {"mean": 0.003, "distribution": "exponential"},
                },
            )
        hops = rest
    for i, sid in enumerate(hops):
        target = hops[i + 1] if i + 1 < len(hops) else "client-1"
        edges.append(
            {
                "id": f"{sid}-out",
                "source": sid,
                "target": target,
                "latency": {"mean": 0.003, "distribution": "exponential"},
            },
        )

    data = {
        "rqs_input": {
            "id": "rqs-1",
            "avg_active_users": {"mean": int(rng.integers(10, 80))},
            "avg_request_per_minute_per_user": {"mean": 20},
            "user_sampling_window": 30,
        },
        "topology_graph": {
            "nodes": {
                "client": {"id": "client-1"},
                **(
                    {
                        "load_balancer": {
                            "id": "lb-1",
                            "algorithms": str(
                                rng.choice(["round_robin", "least_connection"]),
                            ),
                            "server_covered": [s["id"] for s in servers[:2]],
                        },
                    }
                    if use_lb
                    else {}
                ),
                "servers": servers,
            },
            "edges": edges,
        },
        "sim_settings": {"total_simulation_time": 20, "sample_period_s": 0.05},
    }
    return SimulationPayload.model_validate(data)


@pytest.mark.parametrize("case", range(8))
def test_engine_invariants_random_payloads(case: int) -> None:
    rng = np.random.default_rng(1000 + case)
    payload = _random_payload(rng)
    plan = compile_payload(payload)
    engine = Engine(plan, collect_gauges=True, collect_clocks=True)
    final = engine.run_batch(scenario_keys(case, 2))

    for i in range(2):
        # resource conservation at the horizon
        cores_free = np.asarray(final.cores_free[i])
        ram_free = np.asarray(final.ram_free[i])
        assert (cores_free >= 0).all()
        assert (cores_free <= plan.server_cores).all()
        assert (ram_free >= -1e-3).all()
        assert (ram_free <= plan.server_ram + 1e-3).all()

        # gauge series: queue lengths and RAM never negative, RAM <= capacity
        series = np.cumsum(np.asarray(final.gauge[i]), axis=0)[
            1 : plan.n_samples + 1
        ]
        for s in range(plan.n_servers):
            ready = series[:, plan.gauge_ready(s)]
            io = series[:, plan.gauge_io(s)]
            ram = series[:, plan.gauge_ram(s)]
            assert ready.min() >= -1e-3, f"server {s} ready queue negative"
            assert io.min() >= -1e-3
            assert ram.min() >= -1e-3
            assert ram.max() <= float(plan.server_ram[s]) + 1e-3
        for e in range(plan.n_edges):
            assert series[:, plan.gauge_edge(e)].min() >= -1e-3

        # request conservation: everything generated is accounted for.
        # Case 7 used to fail this by 1: the exit branch folds the final
        # client-bound transit into the server-exit event and freed the
        # slot even when the transit landed PAST the horizon, so a
        # horizon-straddling request was neither completed nor in flight.
        # The engine now parks such requests as an un-fireable
        # EV_ARRIVE_CLIENT (the oracle heap holds the same event at the
        # horizon), keeping them in the in-flight term below.
        generated = int(final.n_generated[i])
        completed = int(final.lat_count[i])
        dropped = int(final.n_dropped[i])
        overflow = int(final.n_overflow[i])
        in_flight = int(np.sum(np.asarray(final.req_ev[i]) != 0))
        assert generated == completed + dropped + overflow + in_flight, (
            generated,
            completed,
            dropped,
            overflow,
            in_flight,
        )

        # clocks are consistent: 0 <= start < finish <= horizon
        clock_n = min(int(final.clock_n[i]), final.clock.shape[1])
        clock = np.asarray(final.clock[i][:clock_n])
        if clock_n:
            assert (clock[:, 0] >= 0).all()
            assert (clock[:, 1] > clock[:, 0]).all()
            assert (clock[:, 1] <= plan.horizon + 1e-5).all()


def test_fastpath_invariants_random_payloads() -> None:
    """Fast-path variant on the eligible subset of random payloads."""
    from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine

    checked = 0
    for case in range(16):
        rng = np.random.default_rng(2000 + case)
        payload = _random_payload(rng)
        plan = compile_payload(payload)
        if not plan.fastpath_ok:
            continue
        engine = FastEngine(plan, collect_gauges=True, collect_clocks=True)
        final = engine.run_batch(scenario_keys(case, 2))
        for i in range(2):
            series = np.cumsum(np.asarray(final.gauge[i]), axis=0)[
                1 : plan.n_samples + 1
            ]
            for s in range(plan.n_servers):
                assert series[:, plan.gauge_ready(s)].min() >= -1e-3
                assert series[:, plan.gauge_ram(s)].max() <= (
                    float(plan.server_ram[s]) + 1e-3
                )
            generated = int(final.n_generated[i])
            completed = int(final.lat_count[i])
            dropped = int(final.n_dropped[i])
            overflow = int(final.n_overflow[i])
            # the fast path freezes requests that would act past the horizon
            # instead of tracking them individually: conservation is an
            # inequality (completed + dropped never exceed generated)
            assert completed + dropped <= generated
            assert overflow >= 0
        checked += 1
    assert checked >= 4, f"only {checked} random payloads were eligible"
