"""Exactness of the sort-free stable rank (sortutil) on both lowering
paths: the native FFI kernel (CPU) and the pure-XLA u32 sort path.

The fast path's correctness rests on ``time_rank`` being bit-identical to
``jnp.argsort(where(alive, t, INF))``'s inverse — stable ties, dead lanes
last in lane order — so every adversarial shape is checked against the
tuple argsort on both implementations.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from asyncflow_tpu.engines.jaxsim.params import INF
from asyncflow_tpu.engines.jaxsim.sortutil import (
    _ensure_ffi,
    _time_rank_xla,
    argsort_time,
    time_rank,
)


def _ref_argsort(t, alive):
    return jnp.argsort(jnp.where(alive, t, INF))


def _ref_rank(t, alive):
    n = t.shape[0]
    order = _ref_argsort(t, alive)
    return jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))


def _cases():
    rng = np.random.default_rng(7)
    n = 4096
    t = rng.uniform(0, 600, n).astype(np.float32)
    yield "random+dead", t, rng.uniform(size=n) < 0.7
    yield "heavy-ties", (rng.integers(0, 40, n) * 0.1).astype(np.float32), np.ones(n, bool)
    yield "all-dead", t, np.zeros(n, bool)
    yield "all-equal", np.full(n, 3.25, np.float32), np.ones(n, bool)
    t3 = np.sort(rng.uniform(599, 600, n)).astype(np.float32)
    yield "f32-collisions", t3, rng.uniform(size=n) < 0.9
    yield "negatives", rng.normal(0, 1, n).astype(np.float32), rng.uniform(size=n) < 0.5
    base = np.sort(rng.uniform(0, 600, n)).astype(np.float32)
    yield "near-sorted", base + rng.exponential(0.005, n).astype(np.float32), np.ones(n, bool)
    yield "single", np.array([1.0], np.float32), np.array([True])
    yield "reverse-sorted", np.sort(t)[::-1].copy(), np.ones(n, bool)
    yield (
        "signed-zeros",
        np.array([0.0, -0.0, 1.0, -0.0, 0.0, -1.0], np.float32),
        np.ones(6, bool),
    )


@pytest.mark.parametrize("name,t,alive", list(_cases()), ids=[c[0] for c in _cases()])
def test_time_rank_matches_stable_argsort(name, t, alive):
    tj, aj = jnp.asarray(t), jnp.asarray(alive)
    rank = jax.jit(time_rank)(tj, aj)
    assert bool(jnp.all(rank == _ref_rank(tj, aj)))
    order = jax.jit(argsort_time)(tj, aj)
    assert bool(jnp.all(order == _ref_argsort(tj, aj)))


@pytest.mark.parametrize("mode", ["search", "kvsort", "bitonic"])
@pytest.mark.parametrize("name,t,alive", list(_cases()), ids=[c[0] for c in _cases()])
def test_xla_path_matches_stable_argsort(name, t, alive, mode, monkeypatch):
    """Every pure-XLA rank strategy (what a real TPU lowers) is exact on
    its own: 'search' (sort + searchsorted + tie-fix), 'kvsort' (one
    stable (key, iota) sort), and 'bitonic' (the elementwise sorting
    network) — the AF_TPU_RANK A/B arms."""
    from asyncflow_tpu.engines.jaxsim import sortutil

    monkeypatch.setattr(sortutil, "_RANK_MODE", mode)
    tj = jnp.where(jnp.asarray(alive), jnp.asarray(t), jnp.inf)
    rank = jax.jit(_time_rank_xla)(tj)
    assert bool(jnp.all(rank == _ref_rank(jnp.asarray(t), jnp.asarray(alive))))


@pytest.mark.parametrize("mode", ["search", "kvsort", "bitonic"])
def test_vmapped_rank_matches(mode, monkeypatch):
    """Batched exactly as the scanned fast path ships it to the TPU: the
    rank under vmap, in every AF_TPU_RANK arm."""
    from asyncflow_tpu.engines.jaxsim import sortutil

    monkeypatch.setattr(sortutil, "_RANK_MODE", mode)
    rng = np.random.default_rng(3)
    n = 8192
    base = np.sort(rng.uniform(0, 600, (4, n)), axis=1).astype(np.float32)
    T = jnp.asarray(base + rng.exponential(0.005, (4, n)).astype(np.float32))
    A = jnp.asarray(rng.uniform(size=(4, n)) < 0.95)
    Tinf = jnp.where(A, T, jnp.inf)
    got = jax.jit(jax.vmap(sortutil._time_rank_xla))(Tinf)
    want = jax.vmap(_ref_rank)(T, A)
    assert bool(jnp.all(got == want))
    got_tr = jax.jit(jax.vmap(time_rank))(T, A)
    assert bool(jnp.all(got_tr == want))


def test_ffi_availability_is_reported():
    # Wherever a compiler exists the native kernel must build (a silent
    # fallback would hide a 10x perf regression); compiler-less boxes
    # legitimately degrade to the pure-XLA path.
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no g++: pure-XLA fallback is the supported path")
    assert _ensure_ffi() is True


@pytest.mark.parametrize("side", ["left", "right"])
def test_searchsorted_small_matches_numpy(side, rng):
    """The broadcast compare-count is bit-identical to searchsorted for
    every table size the call sites use, including exact boundary hits."""
    from asyncflow_tpu.engines.jaxsim.sortutil import searchsorted_small

    for nt in (1, 2, 5, 21, 40):
        table = np.sort(
            rng.choice(rng.uniform(0.0, 10.0, nt * 2), nt, replace=True),
        ).astype(np.float32)
        q = rng.uniform(-1.0, 11.0, 300).astype(np.float32)
        q[:nt] = table  # exact hits exercise the <= / < boundary
        want = np.searchsorted(table, q, side=side)
        got = np.asarray(
            searchsorted_small(jnp.asarray(table), jnp.asarray(q), side),
        )
        assert (got == want).all()
    with pytest.raises(ValueError, match="side"):
        searchsorted_small(jnp.zeros(3), jnp.zeros(4), "Right")


@pytest.mark.parametrize("side", ["left", "right"])
def test_searchsorted_large_table_falls_back_exactly(side, rng):
    """Past DENSE_TABLE_MAX the dense (n, nt) compare matrix is a memory
    cliff (a 3600-window offsets table x 1e5 slots is a ~4e8-element
    intermediate), so the helper must switch to the log-n search — and stay
    bit-identical across the threshold."""
    from asyncflow_tpu.engines.jaxsim.sortutil import (
        DENSE_TABLE_MAX,
        searchsorted_small,
    )

    for nt in (DENSE_TABLE_MAX, DENSE_TABLE_MAX + 1, 3600):
        table = np.sort(rng.uniform(0.0, 10.0, nt)).astype(np.float32)
        q = rng.uniform(-1.0, 11.0, 500).astype(np.float32)
        q[:100] = table[:100]  # exact hits exercise the boundary either path
        want = np.searchsorted(table, q, side=side)
        got = np.asarray(
            searchsorted_small(jnp.asarray(table), jnp.asarray(q), side),
        )
        assert (got == want).all(), nt
        assert got.dtype == np.int32


def test_fastpath_windows_past_table_max_bit_identical(monkeypatch):
    """The arrival constructor's window lookup (fastpath ``_arrivals_stream``)
    must survive plans with more windows than DENSE_TABLE_MAX: a 1 s
    sampling window over a 300 s horizon puts a 300-entry int32 offsets
    table through ``searchsorted_small``, and the log-n fallback arm has to
    produce bit-identical engine results to the dense compare arm."""
    import yaml

    from asyncflow_tpu.compiler import compile_payload
    from asyncflow_tpu.engines.jaxsim import sortutil
    from asyncflow_tpu.engines.jaxsim.engine import scenario_keys
    from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine
    from asyncflow_tpu.schemas.payload import SimulationPayload

    data = yaml.safe_load(
        open("tests/integration/data/single_server.yml").read(),
    )
    data["sim_settings"]["total_simulation_time"] = 300
    data["rqs_input"]["user_sampling_window"] = 1
    data["rqs_input"]["avg_active_users"]["mean"] = 5
    plan = compile_payload(SimulationPayload.model_validate(data))
    assert plan.fastpath_ok

    eng = FastEngine(plan)
    assert eng.n_windows > sortutil.DENSE_TABLE_MAX  # the fallback arm runs
    fallback = eng.run_batch(scenario_keys(3, 2))

    # force the dense compare arm on the same 300-entry table (fresh trace:
    # the threshold is read at trace time)
    monkeypatch.setattr(sortutil, "DENSE_TABLE_MAX", 10_000)
    jax.clear_caches()
    dense = FastEngine(plan).run_batch(scenario_keys(3, 2))
    for name in (
        "lat_count", "hist", "lat_sum", "lat_max",
        "n_generated", "n_dropped",
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(fallback, name)),
            np.asarray(getattr(dense, name)),
            err_msg=name,
        )
    assert int(np.asarray(fallback.lat_count).sum()) > 0
