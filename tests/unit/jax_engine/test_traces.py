"""Per-hop request traces on the batched event engine (VERDICT r3 #8).

The reference appends a ``Hop`` in every actor
(`/root/reference/src/asyncflow/runtime/rqs_state.py:12-41`); the oracle
clones that.  The event engine records the same hops in fixed-size
per-request rings and flushes them at completion — these tests pin the
trace structure against the oracle's.
"""

from __future__ import annotations

import pytest
import yaml

from asyncflow_tpu.runtime.runner import SimulationRunner
from asyncflow_tpu.schemas.payload import SimulationPayload

LB = "examples/yaml_input/data/two_servers_lb.yml"


def _payload(horizon: int = 20) -> SimulationPayload:
    data = yaml.safe_load(open(LB).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    return SimulationPayload.model_validate(data)


def _paths(res) -> set:
    return {
        tuple((kind, cid) for kind, cid, _ in trace)
        for trace in res.get_traces().values()
    }


@pytest.mark.parametrize("backend", ["jax", "native"])
def test_traces_match_oracle_structure(backend: str) -> None:
    """Both the batched event engine AND the C++ core (round 5: hop rings
    through the C ABI) must reproduce the oracle's trace structure."""
    if backend == "native":
        from asyncflow_tpu.engines.oracle.native import native_available

        if not native_available():
            # without a compiler the runner would silently fall back to
            # the oracle and this parametrization would pass vacuously
            pytest.skip("no C++ toolchain")
    p = _payload()
    res = SimulationRunner(
        simulation_input=p,
        backend=backend,
        seed=3,
        engine_options={"collect_traces": True},
    ).run()
    orc = SimulationRunner(
        simulation_input=p,
        backend="oracle",
        seed=3,
        engine_options={"collect_traces": True},
    ).run()
    tr = res.get_traces()
    assert len(tr) > 1000
    for trace in tr.values():
        times = [t for _, _, t in trace]
        assert times == sorted(times)
        assert trace[0][0] == "generator"
        assert trace[-1][0] == "client"
    # both engines see exactly the two LB paths, hop for hop
    assert _paths(res) == _paths(orc)


def test_traces_need_event_engine_and_clocks() -> None:
    from asyncflow_tpu.engines.jaxsim.engine import Engine, run_single
    from asyncflow_tpu.compiler import compile_payload

    with pytest.raises(ValueError, match="event engine"):
        run_single(_payload(), engine="fast", collect_traces=True)
    with pytest.raises(ValueError, match="collect_clocks"):
        Engine(compile_payload(_payload()), collect_traces=True)


def test_collect_traces_false_keeps_fast_path() -> None:
    """Explicitly passing collect_traces=False must not crash FastEngine
    (the kwarg is consumed by run_single, not forwarded)."""
    from asyncflow_tpu.engines.jaxsim.engine import run_single

    r = run_single(_payload(horizon=5), seed=1, collect_traces=False)
    assert r.total_generated > 0
    assert r.traces is None
