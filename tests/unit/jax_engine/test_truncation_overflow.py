"""Loud-failure accounting: iteration-cap truncation and clock overflow.

The reference's unbounded Python lists can't overflow silently; our
fixed-shape buffers can, so every capacity cliff must be surfaced
(`/root/reference/src/asyncflow/runtime/actors/server.py:186-193` states the
invariants; SURVEY.md §7 "Variable-length everything" demands explicit
overflow handling).
"""

import dataclasses

import numpy as np
import pytest

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.engines.jaxsim.engine import (
    Engine,
    engine_truncated,
    run_single,
    scenario_keys,
    sweep_results,
)


@pytest.fixture
def plan(minimal_payload):
    return compile_payload(minimal_payload)


class TestIterationCapTruncation:
    def test_capped_run_is_flagged(self, plan) -> None:
        tiny = dataclasses.replace(plan, max_iterations=40)
        eng = Engine(tiny)
        final = eng.run_batch(scenario_keys(0, 2))
        flags = engine_truncated(eng, final)
        assert flags.shape == (2,)
        assert flags.all()

    def test_completed_run_is_not_flagged(self, plan) -> None:
        eng = Engine(plan)
        final = eng.run_batch(scenario_keys(0, 2))
        assert not engine_truncated(eng, final).any()

    def test_sweep_results_carry_the_flag(self, plan, minimal_payload) -> None:
        tiny = dataclasses.replace(plan, max_iterations=40)
        eng = Engine(tiny)
        final = eng.run_batch(scenario_keys(0, 3))
        res = sweep_results(eng, final, minimal_payload.sim_settings)
        assert res.truncated is not None
        assert res.truncated.all()
        # scenario-axis slicing keeps the flag aligned
        assert res[:2].truncated.shape == (2,)

    def test_fastpath_states_never_flag(self, plan) -> None:
        from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine

        assert plan.fastpath_ok, plan.fastpath_reason
        eng = FastEngine(plan)
        final = eng.run_batch(scenario_keys(0, 2))
        flags = engine_truncated(eng, final)
        assert flags.shape == (2,)
        assert not flags.any()

    def test_run_single_warns_on_truncation(self, minimal_payload) -> None:
        import warnings

        import asyncflow_tpu.engines.jaxsim.engine as engine_mod

        plan = compile_payload(minimal_payload)
        tiny = dataclasses.replace(plan, max_iterations=40)
        orig = engine_mod.compile_payload
        engine_mod.compile_payload = lambda _p: tiny
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                run_single(minimal_payload, seed=1, engine="event")
        finally:
            engine_mod.compile_payload = orig
        assert any("iteration safety cap" in str(w.message) for w in caught)


class TestClockOverflow:
    def test_jax_event_engine_warns_and_clamps(self, minimal_payload) -> None:
        with pytest.warns(UserWarning, match="clock table overflow"):
            res = run_single(
                minimal_payload,
                seed=3,
                engine="event",
                max_requests=8,
            )
        assert len(res.rqs_clock) == 8

    def test_no_spurious_warning_without_clocks(self, minimal_payload) -> None:
        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            res = run_single(
                minimal_payload,
                seed=3,
                collect_clocks=False,
            )
        assert not any("clock table overflow" in str(w.message) for w in caught)
        assert res.rqs_clock.shape == (0, 2)

    def test_native_core_warns_and_clamps(self, plan, minimal_payload) -> None:
        from asyncflow_tpu.engines.oracle.native import native_available, run_native

        if not native_available():
            pytest.skip("no C++ toolchain")
        small = dataclasses.replace(plan, max_requests=8)
        with pytest.warns(UserWarning, match="clock table overflow"):
            res = run_native(small, seed=3, settings=minimal_payload.sim_settings)
        assert len(res.rqs_clock) == 8
        # counters still report the full run, not the clamped clock
        assert res.total_generated > 8


class TestCheckpointIdentity:
    def test_identity_depends_on_capacity_knobs(self, minimal_payload) -> None:
        from asyncflow_tpu.parallel.sweep import SweepRunner

        base = SweepRunner(minimal_payload, use_mesh=False)
        bigger = SweepRunner(minimal_payload, use_mesh=False, pool_size=2048)
        assert bigger.plan.pool_size != base.plan.pool_size
        assert base._checkpoint_identity(None) != bigger._checkpoint_identity(None)


class TestUnseededRunsDiffer:
    def test_jax_backend_draws_a_seed_when_none(self, minimal_payload) -> None:
        from asyncflow_tpu.runtime.runner import SimulationRunner

        runs = [
            SimulationRunner(simulation_input=minimal_payload, backend="jax")
            .run()
            .get_latency_stats()["total_requests"]
            for _ in range(2)
        ]
        seeded = [
            SimulationRunner(simulation_input=minimal_payload, backend="jax", seed=0)
            .run()
            .get_latency_stats()["total_requests"]
            for _ in range(2)
        ]
        assert seeded[0] == seeded[1]
        # two unseeded 30 s runs colliding in completion count is ~impossible
        assert runs[0] != runs[1] or runs[0] != seeded[0]
