"""Analyzer unit tests with synthetic results (no simulation run needed),
mirroring the reference's duck-typed-dummy technique
(`/root/reference/tests/unit/metrics/test_analyzer.py:34-60`)."""

import numpy as np
import pytest

from asyncflow_tpu.config.constants import LatencyKey
from asyncflow_tpu.engines.results import SimulationResults
from asyncflow_tpu.metrics.analyzer import ResultsAnalyzer
from asyncflow_tpu.schemas.settings import SimulationSettings


def _results(clock: np.ndarray, horizon: int = 10) -> SimulationResults:
    return SimulationResults(
        settings=SimulationSettings(total_simulation_time=horizon),
        rqs_clock=clock,
        sampled={"ram_in_use": {"srv-1": np.array([1.0, 2.0, 3.0])}},
        server_ids=["srv-1"],
        edge_ids=["e-1"],
    )


def test_latency_stats_exact_values() -> None:
    clock = np.array([[0.0, 1.0], [1.0, 3.0], [2.0, 5.0], [3.0, 7.0]])
    analyzer = ResultsAnalyzer(_results(clock))
    stats = analyzer.get_latency_stats()
    # latencies: 1, 2, 3, 4
    assert stats[LatencyKey.TOTAL_REQUESTS] == 4
    assert stats[LatencyKey.MEAN] == pytest.approx(2.5)
    assert stats[LatencyKey.MEDIAN] == pytest.approx(2.5)
    assert stats[LatencyKey.MIN] == 1.0
    assert stats[LatencyKey.MAX] == 4.0
    assert stats[LatencyKey.P95] == pytest.approx(np.percentile([1, 2, 3, 4], 95))


def test_empty_clock_gives_empty_stats() -> None:
    analyzer = ResultsAnalyzer(_results(np.empty((0, 2))))
    assert analyzer.get_latency_stats() == {}
    assert analyzer.format_latency_stats() == "Latency stats: (empty)"


def test_throughput_bucket_edges() -> None:
    """Completions exactly on a bucket boundary count in that bucket
    (reference scan: finish <= current_end)."""
    clock = np.array([[0.0, 0.5], [0.0, 1.0], [0.0, 1.5], [0.0, 9.99]])
    analyzer = ResultsAnalyzer(_results(clock, horizon=10))
    times, rps = analyzer.get_throughput_series()
    assert times == [float(k) for k in range(1, 11)]
    assert rps[0] == 2.0  # 0.5 and exactly 1.0
    assert rps[1] == 1.0  # 1.5
    assert rps[9] == 1.0  # 9.99
    assert sum(rps) == 4.0


def test_custom_window_preserves_total() -> None:
    rng = np.random.default_rng(3)
    finishes = np.sort(rng.uniform(0, 10, 100))
    clock = np.stack([np.zeros(100), finishes], axis=1)
    analyzer = ResultsAnalyzer(_results(clock, horizon=10))
    _, r1 = analyzer.get_throughput_series()
    _, r2 = analyzer.get_throughput_series(window_s=2.5)
    assert np.isclose(sum(r1), sum(np.asarray(r2) * 2.5))


def test_series_accessors() -> None:
    analyzer = ResultsAnalyzer(_results(np.empty((0, 2))))
    assert analyzer.list_server_ids() == ["srv-1"]
    times, values = analyzer.get_series("ram_in_use", "srv-1")
    assert values.tolist() == [1.0, 2.0, 3.0]
    assert times[0] == 0.0
    assert analyzer.get_metric_map("nonexistent") == {}
    _, missing = analyzer.get_series("ram_in_use", "ghost")
    assert missing.size == 0
