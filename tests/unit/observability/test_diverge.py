"""Divergence-finder comparator: first differing event, aligned context."""

from __future__ import annotations

from asyncflow_tpu.observability.diverge import compare_flight
from asyncflow_tpu.observability.simtrace import (
    FR_ARRIVE_SRV,
    FR_COMPLETE,
    FR_DROP,
    FR_SPAWN,
    FR_TRANSIT,
    FR_WAIT_CPU,
    FlightRecord,
)


def _flight(*event_lists) -> dict[int, FlightRecord]:
    return {
        i: FlightRecord(req=i, events=list(evs))
        for i, evs in enumerate(event_lists)
    }


_BASE = [
    (FR_SPAWN, 0, 0.0),
    (FR_TRANSIT, 0, 0.003),
    (FR_ARRIVE_SRV, 0, 0.003),
    (FR_TRANSIT, 1, 0.020),
    (FR_COMPLETE, -1, 0.020),
]


def test_identical_streams_report_equal() -> None:
    report = compare_flight(_flight(_BASE), _flight(_BASE))
    assert report.equal
    assert report.requests_compared == 1
    assert "no divergence" in report.summary()


def test_time_tolerance_absorbs_float32_noise() -> None:
    """A few microseconds of float32 sim-clock rounding is precision, not
    divergence; past the tolerance it IS the first differing event."""
    shifted = [(c, n, t + 10e-6) for c, n, t in _BASE[1:]]
    near = _flight([_BASE[0], *shifted])
    report = compare_flight(_flight(_BASE), near, tol_us=50.0)
    assert report.equal
    report = compare_flight(_flight(_BASE), near, tol_us=5.0)
    assert not report.equal
    assert report.divergence.kind == "time"
    assert report.divergence.index == 1


def test_code_divergence_localized_with_context() -> None:
    diverged = list(_BASE)
    diverged[3] = (FR_DROP, 1, 0.015)  # delivery became a drop
    diverged[4] = (FR_SPAWN, 0, 0.1)
    report = compare_flight(_flight(_BASE), _flight(diverged), context=2)
    assert not report.equal
    d = report.divergence
    assert (d.request, d.index, d.kind) == (0, 3, "code")
    # aligned windows with the divergence marked
    assert any(line.startswith(">") for line in d.context_oracle)
    assert any("drop" in line for line in d.context_jax)
    assert (
        "first divergence (oracle vs jax) at request 0, event 3"
        in report.summary()
    )


def test_summary_names_the_engine_pair() -> None:
    """CI logs from the fast,event gate must be self-describing: both the
    equal and the diverged summaries carry the compared pair."""
    eq = compare_flight(
        _flight(_BASE), _flight(_BASE), engines=("fast", "event"),
    )
    assert "fast vs event" in eq.summary()
    diverged = list(_BASE)
    diverged[3] = (FR_DROP, 1, 0.015)
    bad = compare_flight(
        _flight(_BASE), _flight(diverged), engines=("fast", "event"),
    )
    assert not bad.equal
    s = bad.summary()
    assert "first divergence (fast vs event)" in s
    assert "  fast: " in s and "  event: " in s


def test_node_divergence() -> None:
    diverged = list(_BASE)
    diverged[2] = (FR_ARRIVE_SRV, 1, 0.003)  # routed to the wrong server
    report = compare_flight(_flight(_BASE), _flight(diverged))
    assert report.divergence.kind == "node"
    assert report.divergence.index == 2


def test_length_divergence_when_prefix_matches() -> None:
    longer = [*_BASE[:3], (FR_WAIT_CPU, 0, 0.003), *_BASE[3:]]
    report = compare_flight(_flight(_BASE), _flight(longer))
    assert not report.equal
    assert report.divergence.kind in ("code", "length")
    assert report.divergence.index == 3


def test_first_diverging_request_wins() -> None:
    """Requests are compared in spawn order: the report localizes the
    EARLIEST diverging request, not an arbitrary one."""
    bad = list(_BASE)
    bad[1] = (FR_TRANSIT, 0, 0.009)
    report = compare_flight(
        _flight(_BASE, _BASE), _flight(_BASE, bad),
    )
    assert report.divergence.request == 1


def test_tail_mismatch_reported_not_diverged() -> None:
    """A request present on one side only (arrival-count tail near the
    horizon) is surfaced but is not a first-divergence."""
    report = compare_flight(_flight(_BASE, _BASE), _flight(_BASE))
    assert report.equal
    assert report.only_oracle == [1]
