"""The fleet-view surfaces: kind="progress" heartbeats, the live follower,
and the static HTML dashboard."""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest
import yaml

from asyncflow_tpu.observability.dashboard import build_dashboard, write_dashboard
from asyncflow_tpu.observability.export import read_run_records
from asyncflow_tpu.observability.live import (
    format_final,
    format_progress,
    iter_records,
    validate_progress_record,
)
from asyncflow_tpu.observability.telemetry import TelemetryConfig
from asyncflow_tpu.parallel import SweepRunner
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"


def _progress_record(done: int, total: int, **over) -> dict:
    meta = {
        "phase": "pipeline",
        "engine": "fast",
        "seed": 0,
        "first_scenario": 0,
        "n_scenarios": total,
        "scenarios_done": done,
        "chunk_rows": 2,
        "elapsed_s": float(done),
        "scenarios_per_second": 2.0,
        "ewma_scenarios_per_second": 2.0,
        "eta_s": float(total - done) / 2.0,
        "n_quarantined": 0,
        "recovery_actions": 0,
    }
    meta.update(over)
    return {
        "schema": "asyncflow-telemetry/1",
        "ts": 0.0,
        "kind": "progress",
        "label": "",
        "pid": 1,
        "meta": meta,
        "phase_totals_s": {},
        "phases": [],
        "compiles": [],
        "counters": {},
    }


def _sweep_record(**meta_over) -> dict:
    rec = _progress_record(8, 8)
    rec["kind"] = "sweep"
    rec["meta"] = {
        "engine": "fast",
        "backend": "cpu",
        "n_scenarios": 8,
        "seed": 0,
        "wall_seconds": 4.0,
        "scenarios_per_second": 2.0,
        "n_quarantined": 0,
        "recovery_actions": 0,
        **meta_over,
    }
    rec["phase_totals_s"] = {"execute": 3.0, "fetch": 0.5}
    rec["compiles"] = [
        {"key": "fast/run_batch", "engine": "fast", "cache_hit": False,
         "compile_s": 1.2},
        {"key": "fast/run_batch", "engine": "fast", "cache_hit": True,
         "compile_s": None},
    ]
    return rec


def test_progress_schema_validator() -> None:
    assert validate_progress_record(_progress_record(2, 8)) == []
    bad = _progress_record(2, 8)
    del bad["meta"]["eta_s"]
    assert any("eta_s" in p for p in validate_progress_record(bad))
    assert validate_progress_record({"kind": "sweep"})


def test_follower_formatting() -> None:
    line = format_progress(_progress_record(2, 8, n_quarantined=1))
    assert "2/8" in line
    assert "quarantined=1" in line
    final = format_final(_sweep_record())
    assert "8 scenarios" in final
    assert "'fast'" in final


def test_iter_records_stops_at_sweep_and_holds_torn_tail(tmp_path) -> None:
    path = tmp_path / "run.jsonl"
    full = json.dumps(_progress_record(2, 8))
    torn = json.dumps(_progress_record(4, 8))
    path.write_text(full + "\n" + torn[: len(torn) // 2])
    got = list(iter_records(path, follow=False))
    assert len(got) == 1  # the torn line is held, not mis-parsed
    path.write_text(
        full + "\n" + torn + "\n" + json.dumps(_sweep_record()) + "\n",
    )
    got = list(iter_records(path, follow=False))
    assert [r["kind"] for r in got] == ["progress", "progress", "sweep"]


def test_dashboard_from_records_only() -> None:
    records = [
        _progress_record(2, 8),
        _progress_record(4, 8),
        _sweep_record(),
    ]
    page = build_dashboard(records)
    for token in ("Summary", "Progress", "Phase timers", "Compile ledger",
                  "<svg", "warm", "cold"):
        assert token in page
    # self-contained: nothing fetched at view time
    assert "http://" not in page
    assert "https://" not in page
    assert "<script" not in page


def test_dashboard_handles_unfinished_run() -> None:
    page = build_dashboard([_progress_record(2, 8)])
    assert "still running" in page


@pytest.mark.slow
def test_sweep_emits_valid_heartbeats_and_dashboard(tmp_path) -> None:
    """End to end: a real sweep's JSONL validates, follows, and renders."""
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = 30
    payload = SimulationPayload.model_validate(data)
    jsonl = tmp_path / "run.jsonl"
    rep = SweepRunner(
        payload,
        use_mesh=False,
        gauge_series=("ram_in_use", ["srv-1"], 1.0),
    ).run(8, seed=3, chunk_size=2, telemetry=TelemetryConfig(jsonl_path=str(jsonl)))

    records = read_run_records(jsonl)
    progress = [r for r in records if r["kind"] == "progress"]
    assert progress, "no heartbeats were emitted"
    for rec in progress:
        assert validate_progress_record(rec) == []
    assert progress[-1]["meta"]["scenarios_done"] == 8
    assert records[-1]["kind"] == "sweep"

    out = subprocess.run(
        [sys.executable, "-m", "asyncflow_tpu.observability.live",
         str(jsonl), "--once"],
        capture_output=True,
        text=True,
        check=True,
    )
    assert "8/8" in out.stdout
    assert "done: 8 scenarios" in out.stdout

    html = write_dashboard(jsonl, tmp_path / "dash.html", report=rep)
    page = html.read_text()
    for token in ("Gauge quantile bands", "srv-1", "Confidence intervals",
                  "Progress", "<svg"):
        assert token in page


def test_dashboard_cli(tmp_path) -> None:
    jsonl = tmp_path / "run.jsonl"
    with jsonl.open("w") as fh:
        for rec in (_progress_record(4, 8), _sweep_record()):
            fh.write(json.dumps(rec) + "\n")
    out = subprocess.run(
        [sys.executable, "-m", "asyncflow_tpu.observability.dashboard",
         str(jsonl)],
        capture_output=True,
        text=True,
        check=True,
    )
    dest = Path(str(jsonl.with_suffix(".html")))
    assert dest.exists()
    assert "wrote" in out.stdout
    assert "<svg" in dest.read_text()
