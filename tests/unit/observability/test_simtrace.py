"""Flight-recorder layout: TraceConfig, decode, canonicalization, export."""

from __future__ import annotations

import numpy as np
import pytest

from asyncflow_tpu.observability.export import (
    SIM_PID_REQUESTS,
    sim_trace_events,
    validate_sim_trace,
)
from asyncflow_tpu.observability.simtrace import (
    FR_ARRIVE_SRV,
    FR_COMPLETE,
    FR_SPAWN,
    FR_TRANSIT,
    FlightRecord,
    TraceConfig,
    canonical_spans,
    decode_breaker,
    decode_flight,
    flight_dropped_events,
)


class TestTraceConfig:
    def test_defaults(self) -> None:
        cfg = TraceConfig()
        assert cfg.sample_requests == 8
        assert cfg.event_slots == 48
        assert cfg.resolution_s is None

    def test_budgets_validated(self) -> None:
        with pytest.raises(ValueError):
            TraceConfig(sample_requests=0)
        with pytest.raises(ValueError):
            TraceConfig(event_slots=1)  # below the 4-slot floor
        with pytest.raises(ValueError):
            TraceConfig(resolution_s=0.0)


class TestDecode:
    def test_rows_without_spawns_omitted(self) -> None:
        ev = np.zeros((3, 4), np.int32)
        node = np.zeros((3, 4), np.int32)
        t = np.zeros((3, 4), np.float32)
        n = np.array([2, 0, 1], np.int32)
        ev[0, :2] = [FR_SPAWN, FR_TRANSIT]
        ev[2, 0] = FR_SPAWN
        flight = decode_flight(ev, node, t, n)
        assert sorted(flight) == [0, 2]
        assert flight[0].codes() == [FR_SPAWN, FR_TRANSIT]

    def test_overflow_is_the_dropped_counter(self) -> None:
        """fr_n keeps counting past the slot budget: the overflow IS the
        explicit truncation signal (ISSUE: no silent ring truncation)."""
        ev = np.full((1, 4), FR_TRANSIT, np.int32)
        ev[0, 0] = FR_SPAWN
        node = np.zeros((1, 4), np.int32)
        t = np.zeros((1, 4), np.float32)
        n = np.array([9], np.int32)  # 9 transitions into 4 slots
        flight = decode_flight(ev, node, t, n)
        assert len(flight[0].events) == 4
        assert flight[0].dropped == 5
        assert flight_dropped_events(flight) == 5
        assert "5 later event(s) dropped" in flight[0].describe()[-1]

    def test_decode_breaker(self) -> None:
        out = decode_breaker(
            np.array([1.0, 2.0, 0.0]),
            np.array([0, 1, 0]),
            np.array([1, 2, 0]),
            2,
        )
        assert out == [(1.0, 0, 1), (2.0, 1, 2)]


class TestCanonicalSpans:
    def _rec(self, events) -> dict[int, FlightRecord]:
        return {0: FlightRecord(req=0, events=events)}

    def test_relative_and_quantized(self) -> None:
        spans = canonical_spans(
            self._rec(
                [(FR_SPAWN, 0, 10.0), (FR_TRANSIT, 1, 10.0035)],
            ),
        )
        assert spans[0] == ((FR_SPAWN, 0, 0), (FR_TRANSIT, 1, 3500))

    def test_horizon_filters_forward_dated_events(self) -> None:
        """The jax engine records exit deliveries the oracle heap never
        executes (t >= horizon): canonicalization drops them from both."""
        spans = canonical_spans(
            self._rec(
                [(FR_SPAWN, 0, 59.0), (FR_COMPLETE, -1, 60.5)],
            ),
            horizon=60.0,
        )
        assert spans[0] == ((FR_SPAWN, 0, 0),)

    def test_empty_after_filter_omitted(self) -> None:
        spans = canonical_spans(
            self._rec([(FR_SPAWN, 0, 61.0)]), horizon=60.0,
        )
        assert spans == {}


class _Settings:
    sample_period_s = 0.1
    total_simulation_time = 10


class _Results:
    """Minimal SimulationResults stand-in for the exporter."""

    settings = _Settings()
    server_ids = ["srv-1"]
    edge_ids = ["e-in", "e-out"]
    breaker_timeline = [(1.5, 0, 1), (4.5, 0, 2)]
    flight = {
        0: FlightRecord(
            req=0,
            events=[
                (FR_SPAWN, 0, 1.0),
                (FR_TRANSIT, 0, 1.1),
                (FR_ARRIVE_SRV, 0, 1.1),
                (FR_TRANSIT, 1, 1.4),
                (FR_COMPLETE, -1, 1.4),
            ],
        ),
    }
    sampled = {
        "ready_queue_len": {"srv-1": np.array([0.0, 1.0, 2.0, 1.0])},
        "edge_concurrent_connection": {"e-in": np.array([0.0, 1.0, 0.0, 0.0])},
    }


class TestSimTraceExport:
    def test_roundtrip_validates(self) -> None:
        events = sim_trace_events(_Results())
        doc = {"displayTimeUnit": "ms", "traceEvents": events}
        assert validate_sim_trace(doc) == []
        # one thread per traced request, spans in simulated microseconds
        spans = [e for e in events if e["ph"] == "X"]
        assert any(e["name"] == "transit e-in" for e in spans)
        tids = {
            e["tid"]
            for e in events
            if e.get("pid") == SIM_PID_REQUESTS and e["ph"] == "X"
        }
        assert tids == {1}
        counters = [e for e in events if e["ph"] == "C"]
        assert any("queue depth" in e["name"] for e in counters)
        assert any("breaker" in e["name"] for e in counters)

    def test_resolution_strides_counters(self) -> None:
        fine = [
            e for e in sim_trace_events(_Results()) if e["ph"] == "C"
            and "queue depth" in e["name"]
        ]
        coarse = [
            e
            for e in sim_trace_events(_Results(), resolution_s=0.2)
            if e["ph"] == "C" and "queue depth" in e["name"]
        ]
        assert len(coarse) == (len(fine) + 1) // 2

    def test_validator_rejects_malformed(self) -> None:
        assert validate_sim_trace({}) == ["missing traceEvents list"]
        bad = {
            "traceEvents": [
                {"ph": "X", "pid": 1, "name": "x", "ts": 0.0},  # no dur
                {"ph": "C", "pid": 1, "name": "c", "ts": 0.0, "args": {"v": "s"}},
            ],
        }
        problems = validate_sim_trace(bad)
        assert any("without dur" in p for p in problems)
        assert any("non-numeric counter" in p for p in problems)
