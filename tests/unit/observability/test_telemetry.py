"""Telemetry contracts: phase-record schema, compile-ledger round trip,
Chrome-trace export, report summaries, and the determinism guarantee
(telemetry on/off yields bit-identical simulation results)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from asyncflow_tpu.observability import (
    PHASES,
    CompileLedger,
    PhaseTimer,
    RunTelemetry,
    TelemetryConfig,
    current_telemetry,
    load_chrome_trace,
    read_run_records,
    validate_run_record,
    write_chrome_trace,
)
from asyncflow_tpu.observability.report import (
    format_summary,
    load_trace,
    summarize_trace,
)


# ---------------------------------------------------------------------------
# phase timer
# ---------------------------------------------------------------------------


def test_phase_timer_records_sections_and_events() -> None:
    timer = PhaseTimer()
    with timer.section("execute", chunk=0, meta={"take": 8}):
        pass
    with timer.section("execute", chunk=1):
        pass
    with timer.section("fetch"):
        pass
    assert set(timer.sections) == {"execute", "fetch"}
    assert len(timer.events) == 3
    assert [e.chunk for e in timer.events] == [0, 1, None]
    assert timer.events[0].meta == {"take": 8}
    # sections accumulate across chunks
    per_event = sum(e.duration_s for e in timer.events if e.name == "execute")
    assert timer.sections["execute"] == pytest.approx(per_event)


def test_phase_timer_external_record() -> None:
    timer = PhaseTimer()
    timer.record("validate", 0.25)
    timer.record("validate", 0.25)
    assert timer.sections["validate"] == pytest.approx(0.5)
    assert len(timer.events) == 2


def test_phase_totals_orders_canonical_first() -> None:
    timer = PhaseTimer()
    timer.record("x-custom", 1.0)
    timer.record("execute", 1.0)
    timer.record("build_plan", 1.0)
    assert list(timer.phase_totals()) == ["build_plan", "execute", "x-custom"]


# ---------------------------------------------------------------------------
# run-record schema
# ---------------------------------------------------------------------------


def _fresh_record(tmp_path, *, jsonl=None) -> dict:
    cfg = TelemetryConfig(
        jsonl_path=jsonl,
        ledger_path=tmp_path / "ledger.jsonl",
        label="test",
    )
    tel = RunTelemetry(cfg, kind="sweep")
    with tel:
        with tel.phase("execute", chunk=0):
            pass
        tel.timer.record("build_plan", 0.01)
    return tel.finalize(
        counters={
            "completed": 10,
            "generated": 12,
            "dropped": 2,
            "overflow": 0,
            "rejected": 0,
            "truncated": 0,
        },
        engine="fast",
    )


def test_run_record_schema_is_valid(tmp_path) -> None:
    record = _fresh_record(tmp_path)
    assert validate_run_record(record) == []
    assert record["schema"].startswith("asyncflow-telemetry/")
    assert record["meta"]["engine"] == "fast"
    assert record["counters"]["completed"] == 10
    assert {e["name"] for e in record["phases"]} == {"execute", "build_plan"}


def test_run_record_schema_catches_drift(tmp_path) -> None:
    record = _fresh_record(tmp_path)
    broken = dict(record)
    del broken["counters"]
    assert any("counters" in p for p in validate_run_record(broken))
    typo = dict(record)
    typo["phase_totals_s"] = {"exekute": 1.0}
    assert any("exekute" in p for p in validate_run_record(typo))
    bad_phase = dict(record)
    bad_phase["phases"] = [{"name": "execute"}]
    assert any("start_s" in p for p in validate_run_record(bad_phase))


def test_run_record_jsonl_round_trip(tmp_path) -> None:
    out = tmp_path / "runs.jsonl"
    _fresh_record(tmp_path, jsonl=out)
    _fresh_record(tmp_path, jsonl=out)
    records = read_run_records(out)
    assert len(records) == 2
    for record in records:
        assert validate_run_record(record) == []


def test_finalize_is_idempotent(tmp_path) -> None:
    out = tmp_path / "runs.jsonl"
    cfg = TelemetryConfig(jsonl_path=out, ledger_path=tmp_path / "l.jsonl")
    tel = RunTelemetry(cfg)
    with tel:
        pass
    first = tel.finalize(counters={"completed": 1})
    assert tel.finalize() is first
    assert len(read_run_records(out)) == 1


def test_context_installs_and_resets_current(tmp_path) -> None:
    cfg = TelemetryConfig(ledger_path=tmp_path / "l.jsonl")
    tel = RunTelemetry(cfg)
    assert current_telemetry() is None
    with tel:
        assert current_telemetry() is tel
    assert current_telemetry() is None


# ---------------------------------------------------------------------------
# compile ledger
# ---------------------------------------------------------------------------


def test_ledger_cold_then_warm_round_trip(tmp_path) -> None:
    path = tmp_path / "compile_ledger.jsonl"
    cold = CompileLedger(path)
    entry = cold.record(
        "prog-a", engine="fast", variant="scan", compile_s=1.5, lower_s=0.1,
    )
    assert entry["cache_hit"] is False
    # a fresh process (new ledger object, same file) sees the warm entry
    warm = CompileLedger(path)
    assert warm.seen("prog-a")
    entry2 = warm.record("prog-a", engine="fast", variant="scan", compile_s=0.2)
    assert entry2["cache_hit"] is True
    # a different program shape is cold again
    entry3 = warm.record("prog-b", engine="event", compile_s=2.0)
    assert entry3["cache_hit"] is False
    entries = CompileLedger(path).entries()
    assert [e["cache_hit"] for e in entries] == [False, True, False]
    assert all(e["schema"].startswith("asyncflow-compile-ledger/") for e in entries)


def test_ledger_survives_torn_tail_line(tmp_path) -> None:
    path = tmp_path / "ledger.jsonl"
    CompileLedger(path).record("prog-a", engine="fast")
    with path.open("a") as fh:
        fh.write('{"key": "prog-tor')  # killed mid-write
    ledger = CompileLedger(path)
    assert ledger.seen("prog-a")
    assert not ledger.seen("prog-tor")


# ---------------------------------------------------------------------------
# chrome trace export
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["trace.json", "trace.json.gz"])
def test_chrome_trace_write_and_load(tmp_path, name) -> None:
    timer = PhaseTimer()
    with timer.section("execute", chunk=0):
        pass
    timer.record("build_plan", 0.5)
    path = tmp_path / name
    write_chrome_trace(path, timer, counters={"completed": 3}, label="t")
    trace = load_chrome_trace(path)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in spans} == {"execute", "build_plan"}
    for e in spans:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert e["pid"] == 1 and e["tid"] == 1
    counter_events = [e for e in events if e["ph"] == "C"]
    assert counter_events and counter_events[0]["args"] == {"completed": 3}
    # the library loader reads its own output too (format parity with the
    # jax.profiler traces)
    assert "traceEvents" in load_trace(path)


# ---------------------------------------------------------------------------
# report (the promoted trace_summary)
# ---------------------------------------------------------------------------


def _synthetic_device_trace() -> dict:
    return {
        "traceEvents": [
            {"ph": "M", "pid": 7, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "python host"}},
            {"ph": "X", "pid": 7, "tid": 1, "name": "sort.1", "dur": 500,
             "ts": 0, "args": {"source": "sortutil.py:120"}},
            {"ph": "X", "pid": 7, "tid": 1, "name": "sort.1", "dur": 250,
             "ts": 600, "args": {"source": "sortutil.py:120"}},
            {"ph": "X", "pid": 7, "tid": 1, "name": "scatter.2", "dur": 100,
             "ts": 900, "args": {}},
            # outermost jit container: excluded from totals
            {"ph": "X", "pid": 7, "tid": 1, "name": "jit_run", "dur": 9999,
             "ts": 0, "args": {}},
            # host event: not device time
            {"ph": "X", "pid": 1, "tid": 1, "name": "dispatch", "dur": 400,
             "ts": 0, "args": {}},
        ],
    }


def test_summarize_trace_attributes_device_time() -> None:
    summary = summarize_trace(_synthetic_device_trace())
    assert summary.total_us == 850
    assert summary.by_op == {"sort.1": 750, "scatter.2": 100}
    assert summary.by_source == {"sortutil.py:120": 750}
    assert summary.top_ops(1) == [("sort.1", 750)]
    text = format_summary(summary, top=5)
    assert "sort.1" in text and "sortutil.py:120" in text


def test_summary_smoke_schema() -> None:
    """Smoke-tier schema test: a synthetic record validates end to end
    without touching jax (wired into scripts/run_smoke.sh)."""
    timer = PhaseTimer()
    for name in PHASES:
        timer.record(name, 0.001)
    record = {
        "schema": "asyncflow-telemetry/1",
        "ts": 0.0,
        "kind": "sweep",
        "phase_totals_s": timer.phase_totals(),
        "phases": [e.as_dict() for e in timer.events],
        "compiles": [{"key": "k", "engine": "fast", "cache_hit": False}],
        "counters": {"completed": 1},
    }
    assert validate_run_record(record) == []


# ---------------------------------------------------------------------------
# determinism + live integration (jax; CPU backend)
# ---------------------------------------------------------------------------


def test_sweep_telemetry_off_on_bit_identical(tmp_path, minimal_payload) -> None:
    """The acceptance bar: telemetry on produces bit-identical metrics AND
    a valid run record + ledger + loadable Chrome trace."""
    from asyncflow_tpu.parallel.sweep import SweepRunner

    cfg = TelemetryConfig(
        jsonl_path=tmp_path / "run.jsonl",
        trace_path=tmp_path / "trace.json",
        ledger_path=tmp_path / "ledger.jsonl",
    )
    on = SweepRunner(minimal_payload, use_mesh=False, telemetry=cfg)
    rep_on = on.run(8, seed=11, chunk_size=8)
    off = SweepRunner(minimal_payload, use_mesh=False)
    rep_off = off.run(8, seed=11, chunk_size=8)

    assert np.array_equal(rep_on.results.completed, rep_off.results.completed)
    assert np.array_equal(
        rep_on.results.latency_hist, rep_off.results.latency_hist,
    )
    assert np.array_equal(rep_on.results.latency_sum, rep_off.results.latency_sum)

    # PR 16 streams kind="progress" heartbeats into the same sink;
    # the contract here is the single kind="sweep" run record
    [record] = [
        r for r in read_run_records(cfg.jsonl_path)
        if r["kind"] == "sweep"
    ]
    assert validate_run_record(record) == []
    assert record["meta"]["engine"] == on.engine_kind
    assert record["counters"] == rep_on.results.counters().as_dict()
    # per-chunk phases present
    assert any(p.get("chunk") == 0 for p in record["phases"])
    for phase in ("build_plan", "transfer", "execute", "fetch", "postprocess"):
        assert phase in record["phase_totals_s"], phase
    # cold run wrote exactly the compile the engine performed, as a miss
    assert record["compiles"] and record["compiles"][0]["cache_hit"] is False
    # the ledger marks a fresh engine's identical program warm
    warm = SweepRunner(minimal_payload, use_mesh=False, telemetry=cfg)
    warm.run(8, seed=11, chunk_size=8)
    records = [
        r for r in read_run_records(cfg.jsonl_path)
        if r["kind"] == "sweep"
    ]
    assert records[-1]["compiles"], "warm engine should still record a compile"
    assert records[-1]["compiles"][0]["cache_hit"] is True
    trace = load_chrome_trace(cfg.trace_path)
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])


def test_single_runner_telemetry_matches_plain_run(tmp_path) -> None:
    from asyncflow_tpu.runtime.runner import SimulationRunner

    path = "tests/integration/data/single_server.yml"
    cfg = TelemetryConfig(
        jsonl_path=tmp_path / "runs.jsonl",
        ledger_path=tmp_path / "ledger.jsonl",
    )
    with_tel = SimulationRunner.from_yaml(
        path, backend="oracle", seed=5, telemetry=cfg,
    ).run()
    plain = SimulationRunner.from_yaml(path, backend="oracle", seed=5).run()
    assert np.array_equal(with_tel.results.rqs_clock, plain.results.rqs_clock)
    [record] = read_run_records(cfg.jsonl_path)
    assert validate_run_record(record) == []
    assert record["kind"] == "run"
    assert record["meta"]["engine"] == "oracle"
    assert record["phase_totals_s"]["validate"] > 0
    assert record["counters"]["completed"] == plain.results.rqs_clock.shape[0]


def test_instrument_jit_is_transparent_without_telemetry() -> None:
    import jax
    import jax.numpy as jnp

    from asyncflow_tpu.observability import instrument_jit

    fn = instrument_jit(jax.jit(lambda x: x * 2), engine="test")
    x = jnp.arange(4.0)
    assert np.array_equal(np.asarray(fn(x)), np.asarray(x) * 2)
    # jit attributes pass through (lower_tpu-style AOT callers rely on it)
    assert hasattr(fn, "lower") and hasattr(fn, "trace")


def test_instrument_jit_records_compile_under_telemetry(tmp_path) -> None:
    import jax
    import jax.numpy as jnp

    from asyncflow_tpu.observability import instrument_jit

    fn = instrument_jit(jax.jit(lambda x: x + 1), engine="test", variant="v")
    cfg = TelemetryConfig(ledger_path=tmp_path / "ledger.jsonl")
    tel = RunTelemetry(cfg)
    with tel:
        y1 = fn(jnp.arange(8.0))
        y2 = fn(jnp.arange(8.0))  # same signature: no second compile
        y3 = fn(jnp.arange(4.0))  # new shape: second ledger entry
    assert np.array_equal(np.asarray(y1), np.arange(8.0) + 1)
    assert np.array_equal(np.asarray(y2), np.arange(8.0) + 1)
    assert np.array_equal(np.asarray(y3), np.arange(4.0) + 1)
    assert len(tel.compiles) == 2
    assert tel.compiles[0]["engine"] == "test"
    assert tel.compiles[0]["lower_s"] is not None
    assert tel.compiles[0]["compile_s"] is not None


def test_default_ledger_path_lives_inside_the_cache_dir(monkeypatch) -> None:
    """The ledger shares the compile cache's directory (and lifecycle): it
    used to sit BESIDE .jax_cache — the repo root with the default cache —
    where generated JSONL kept landing in commits."""
    import os

    from asyncflow_tpu.observability import default_ledger_path
    from asyncflow_tpu.utils.compile_cache import ENV_VAR

    monkeypatch.setenv(ENV_VAR, "/tmp/some_cache_dir")
    assert default_ledger_path() == os.path.join(
        "/tmp/some_cache_dir", "compile_ledger.jsonl",
    )
    monkeypatch.delenv(ENV_VAR)
    from asyncflow_tpu.utils.compile_cache import cache_location

    assert os.path.dirname(default_ledger_path()) == cache_location()
