"""Unit tests for the oracle DES kernel primitives."""

from asyncflow_tpu.engines.oracle.kernel import (
    AcquireAmount,
    AcquireToken,
    FifoContainer,
    FifoTokens,
    Sim,
    Timeout,
)


def test_heap_ordering_and_until_exclusive() -> None:
    sim = Sim()
    seen: list[tuple[float, str]] = []
    sim.at(2.0, lambda: seen.append((sim.now, "b")))
    sim.at(1.0, lambda: seen.append((sim.now, "a")))
    sim.at(5.0, lambda: seen.append((sim.now, "never")))
    sim.run(until=5.0)
    assert seen == [(1.0, "a"), (2.0, "b")]
    assert sim.now == 5.0


def test_same_time_fifo_order() -> None:
    sim = Sim()
    seen: list[str] = []
    sim.at(1.0, lambda: seen.append("first"))
    sim.at(1.0, lambda: seen.append("second"))
    sim.run(until=2.0)
    assert seen == ["first", "second"]


def test_process_timeout_chain() -> None:
    sim = Sim()
    marks: list[float] = []

    def proc():
        yield Timeout(1.0)
        marks.append(sim.now)
        yield Timeout(2.5)
        marks.append(sim.now)

    sim.process(proc())
    sim.run(until=10.0)
    assert marks == [1.0, 3.5]


def test_tokens_fifo_wakeup_order() -> None:
    sim = Sim()
    tokens = FifoTokens(sim, capacity=1)
    order: list[str] = []

    def proc(name: str, hold: float):
        yield AcquireToken(tokens)
        order.append(f"{name}@{sim.now}")
        yield Timeout(hold)
        tokens.release()

    sim.process(proc("p1", 1.0))
    sim.process(proc("p2", 1.0))
    sim.process(proc("p3", 1.0))
    sim.run(until=10.0)
    assert order == ["p1@0.0", "p2@1.0", "p3@2.0"]


def test_tokens_would_block() -> None:
    sim = Sim()
    tokens = FifoTokens(sim, capacity=2)
    assert not tokens.would_block

    def hold():
        yield AcquireToken(tokens)
        yield Timeout(5.0)
        tokens.release()

    sim.process(hold())
    sim.process(hold())
    sim.run(until=1.0)
    assert tokens.would_block


def test_container_head_of_line_blocking() -> None:
    """A large waiting request blocks later smaller ones (strict FIFO)."""
    sim = Sim()
    ram = FifoContainer(sim, capacity=100.0)
    granted: list[str] = []

    def taker(name: str, amount: float, hold: float):
        yield AcquireAmount(ram, amount)
        granted.append(f"{name}@{sim.now}")
        yield Timeout(hold)
        ram.release(amount)

    sim.process(taker("big0", 80.0, 4.0))     # holds 80 until t=4
    sim.process(taker("big1", 50.0, 1.0))     # blocks (only 20 free)
    sim.process(taker("small", 10.0, 1.0))    # would fit, must wait behind big1
    sim.run(until=20.0)
    assert granted == ["big0@0.0", "big1@4.0", "small@4.0"]
    assert ram.level == 100.0


def test_container_multiple_grants_on_release() -> None:
    sim = Sim()
    ram = FifoContainer(sim, capacity=100.0)
    granted: list[str] = []

    def taker(name: str, amount: float, hold: float):
        yield AcquireAmount(ram, amount)
        granted.append(name)
        yield Timeout(hold)
        ram.release(amount)

    sim.process(taker("a", 100.0, 2.0))
    sim.process(taker("b", 40.0, 10.0))
    sim.process(taker("c", 40.0, 10.0))
    sim.run(until=3.0)
    # releasing 100 at t=2 must grant both b and c
    assert granted == ["a", "b", "c"]
