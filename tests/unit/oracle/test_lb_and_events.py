"""Unit tests for LB routing and event injection in the oracle engine."""

import numpy as np
import pytest

from asyncflow_tpu.engines.oracle.engine import OracleEngine
from asyncflow_tpu.schemas.payload import SimulationPayload


def _lb_payload(algorithm: str = "round_robin", horizon: int = 40) -> SimulationPayload:
    def server(sid: str) -> dict:
        return {
            "id": sid,
            "server_resources": {"cpu_cores": 1, "ram_mb": 1024},
            "endpoints": [
                {
                    "endpoint_name": "/api",
                    "steps": [
                        {"kind": "initial_parsing", "step_operation": {"cpu_time": 0.001}},
                        {"kind": "io_wait", "step_operation": {"io_waiting_time": 0.01}},
                    ],
                },
            ],
        }

    def edge(eid: str, src: str, dst: str) -> dict:
        return {
            "id": eid,
            "source": src,
            "target": dst,
            "latency": {"mean": 0.002, "distribution": "exponential"},
            "dropout_rate": 0.0,
        }

    return SimulationPayload.model_validate(
        {
            "rqs_input": {
                "id": "rqs-1",
                "avg_active_users": {"mean": 60},
                "avg_request_per_minute_per_user": {"mean": 20},
                "user_sampling_window": 60,
            },
            "topology_graph": {
                "nodes": {
                    "client": {"id": "client-1"},
                    "load_balancer": {
                        "id": "lb-1",
                        "algorithms": algorithm,
                        "server_covered": ["srv-1", "srv-2"],
                    },
                    "servers": [server("srv-1"), server("srv-2")],
                },
                "edges": [
                    edge("gen-client", "rqs-1", "client-1"),
                    edge("client-lb", "client-1", "lb-1"),
                    edge("lb-srv1", "lb-1", "srv-1"),
                    edge("lb-srv2", "lb-1", "srv-2"),
                    edge("srv1-client", "srv-1", "client-1"),
                    edge("srv2-client", "srv-2", "client-1"),
                ],
            },
            "sim_settings": {
                "total_simulation_time": horizon,
                "sample_period_s": 0.01,
            },
        },
    )


def test_round_robin_balances_identical_servers() -> None:
    payload = _lb_payload("round_robin")
    results = OracleEngine(payload, seed=21).run()
    cc = results.sampled["edge_concurrent_connection"]
    m1 = float(np.mean(cc["lb-srv1"]))
    m2 = float(np.mean(cc["lb-srv2"]))
    assert m1 > 0 and m2 > 0
    assert abs(m1 - m2) / ((m1 + m2) / 2) < 0.25


def test_least_connection_prefers_first_edge_on_ties() -> None:
    """Reference-faithful tie behavior: `min` picks the first edge in order,
    so with short transits (mostly-idle edges) traffic skews heavily to the
    first LB edge (`/root/reference/src/asyncflow/runtime/actors/routing/
    lb_algorithms.py:10-20`)."""
    payload = _lb_payload("least_connection")
    results = OracleEngine(payload, seed=21).run()
    cc = results.sampled["edge_concurrent_connection"]
    m1 = float(np.mean(cc["lb-srv1"]))
    m2 = float(np.mean(cc["lb-srv2"]))
    assert m1 > m2


def test_outage_redirects_traffic() -> None:
    payload = _lb_payload()
    data = payload.model_dump()
    data["events"] = [
        {
            "event_id": "ev-1",
            "target_id": "srv-2",
            "start": {"kind": "server_down", "t_start": 0.0},
            "end": {"kind": "server_up", "t_end": 40.0},
        },
    ]
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=23).run()
    ram2 = results.sampled["ram_in_use"]["srv-2"]
    cc = results.sampled["edge_concurrent_connection"]
    # srv-2 receives nothing for the whole run
    assert float(np.max(cc["lb-srv2"])) == 0.0
    assert float(np.max(ram2)) == 0.0
    assert float(np.mean(cc["lb-srv1"])) > 0.0
    # system still completes requests through srv-1
    assert results.rqs_clock.shape[0] > 0


def test_outage_window_recovers() -> None:
    payload = _lb_payload(horizon=60)
    data = payload.model_dump()
    data["events"] = [
        {
            "event_id": "ev-1",
            "target_id": "srv-2",
            "start": {"kind": "server_down", "t_start": 10.0},
            "end": {"kind": "server_up", "t_end": 30.0},
        },
    ]
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=29).run()
    cc2 = results.sampled["edge_concurrent_connection"]["lb-srv2"]
    period = payload.sim_settings.sample_period_s
    # samples land at k*period starting after one period
    during = cc2[int(12 / period) : int(28 / period)]
    after = cc2[int(32 / period) :]
    assert float(np.max(during)) == 0.0
    assert float(np.max(after)) > 0.0


def test_spike_superposition_raises_latency() -> None:
    payload = _lb_payload(horizon=30)
    data = payload.model_dump()
    data["events"] = [
        {
            "event_id": f"ev-{i}",
            "target_id": "client-lb",
            "start": {
                "kind": "network_spike_start",
                "t_start": 5.0,
                "spike_s": 0.05,
            },
            "end": {"kind": "network_spike_end", "t_end": 25.0},
        }
        for i in range(2)
    ]
    payload = SimulationPayload.model_validate(data)
    base = OracleEngine(_lb_payload(horizon=30), seed=31).run()
    spiked = OracleEngine(payload, seed=31).run()
    # two superposed 50ms spikes: mean latency up by roughly 100ms * active share
    assert float(np.mean(spiked.latencies)) > float(np.mean(base.latencies)) + 0.05
