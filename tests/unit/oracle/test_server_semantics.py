"""Unit tests for server semantics through the oracle engine.

Mirrors the reference's stub-actor technique
(`/root/reference/tests/unit/runtime/actors/test_server.py`): tiny scenarios
with deterministic pieces isolate one behavior at a time.
"""

import numpy as np

from asyncflow_tpu.engines.oracle.engine import OracleEngine
from asyncflow_tpu.schemas.payload import SimulationPayload


def _payload(minimal_payload: SimulationPayload, **server_overrides) -> SimulationPayload:
    data = minimal_payload.model_dump()
    server = data["topology_graph"]["nodes"]["servers"][0]
    server.update(server_overrides)
    return SimulationPayload.model_validate(data)


def _zero_dropout(data: dict) -> None:
    for edge in data["topology_graph"]["edges"]:
        edge["dropout_rate"] = 0.0


def test_single_server_latency_composition(minimal_payload) -> None:
    """Latency ~= edge delays + cpu + io under light load."""
    engine = OracleEngine(minimal_payload, seed=7)
    results = engine.run()
    assert results.total_generated > 0
    lat = results.latencies
    assert lat.size > 0
    # cpu 1ms + io 10ms + 3 exponential edges with 3ms mean each ≈ 20ms
    assert 0.011 < float(np.mean(lat)) < 0.045
    # no latency below the deterministic service floor
    assert float(np.min(lat)) >= 0.011


def test_cpu_contention_grows_ready_queue(minimal_payload) -> None:
    """A cpu-bound endpoint at saturation must show ready-queue > 0 samples."""
    data = minimal_payload.model_dump()
    _zero_dropout(data)
    server = data["topology_graph"]["nodes"]["servers"][0]
    server["endpoints"] = [
        {
            "endpoint_name": "cpu-heavy",
            "steps": [
                {"kind": "cpu_bound_operation", "step_operation": {"cpu_time": 0.08}},
            ],
        },
    ]
    data["rqs_input"]["avg_active_users"]["mean"] = 60  # ~20 rps vs 12.5 capacity
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=3).run()
    ready = results.sampled["ready_queue_len"]["srv-1"]
    assert float(np.max(ready)) >= 1.0
    # saturated single core: io queue must stay empty (no io steps)
    io = results.sampled["event_loop_io_sleep"]["srv-1"]
    assert float(np.max(io)) == 0.0


def test_ram_blocking_limits_concurrency(minimal_payload) -> None:
    """RAM capacity caps concurrent in-server requests."""
    data = minimal_payload.model_dump()
    _zero_dropout(data)
    server = data["topology_graph"]["nodes"]["servers"][0]
    server["server_resources"]["ram_mb"] = 256  # only 2 x 100MB fit
    server["endpoints"] = [
        {
            "endpoint_name": "ram-heavy",
            "steps": [
                {"kind": "initial_parsing", "step_operation": {"cpu_time": 0.0001}},
                {"kind": "ram", "step_operation": {"necessary_ram": 100}},
                {"kind": "io_wait", "step_operation": {"io_waiting_time": 0.05}},
            ],
        },
    ]
    data["rqs_input"]["avg_active_users"]["mean"] = 200
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=11).run()
    ram = results.sampled["ram_in_use"]["srv-1"]
    assert float(np.max(ram)) <= 200.0  # never above two concurrent working sets
    assert float(np.max(ram)) > 0.0


def test_io_queue_counts_sleeping_requests(minimal_payload) -> None:
    """Long io with fast cpu: io queue sees many concurrent sleepers."""
    data = minimal_payload.model_dump()
    _zero_dropout(data)
    data["rqs_input"]["avg_active_users"]["mean"] = 100
    server = data["topology_graph"]["nodes"]["servers"][0]
    server["endpoints"][0]["steps"] = [
        {"kind": "initial_parsing", "step_operation": {"cpu_time": 0.0001}},
        {"kind": "io_wait", "step_operation": {"io_waiting_time": 0.2}},
    ]
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=5).run()
    io = results.sampled["event_loop_io_sleep"]["srv-1"]
    # ~33 rps * 0.2 s io ≈ 6-7 concurrent sleepers on average
    assert float(np.mean(io)) > 2.0


def test_dropout_excludes_requests_from_clock(minimal_payload) -> None:
    data = minimal_payload.model_dump()
    for edge in data["topology_graph"]["edges"]:
        edge["dropout_rate"] = 0.5
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=13).run()
    assert results.total_dropped > 0
    # completions + drops cannot exceed generated (some still in flight at T)
    assert results.rqs_clock.shape[0] + results.total_dropped <= results.total_generated
    # with 50% dropout on each of 3 hops, completions << generated
    assert results.rqs_clock.shape[0] < results.total_generated * 0.3


def test_full_dropout_completes_nothing(minimal_payload) -> None:
    data = minimal_payload.model_dump()
    data["topology_graph"]["edges"][0]["dropout_rate"] = 1.0
    payload = SimulationPayload.model_validate(data)
    results = OracleEngine(payload, seed=17).run()
    assert results.rqs_clock.shape[0] == 0
    assert results.total_dropped == results.total_generated


def test_traces_record_the_full_request_path(minimal_payload) -> None:
    """Tracing mirrors the reference's hop history: generator, each edge,
    client forward, server, return edge, client completion."""
    results = OracleEngine(minimal_payload, seed=19, collect_traces=True).run()
    traces = results.traces
    assert traces
    trace = next(iter(traces.values()))
    kinds = [kind for kind, _, _ in trace]
    assert kinds == [
        "generator",
        "network_connection",
        "client",
        "network_connection",
        "server",
        "network_connection",
        "client",
    ]
    times = [t for _, _, t in trace]
    assert times == sorted(times)
    # every completed request has a trace; dropped ones do not
    assert len(traces) == results.rqs_clock.shape[0]


def test_traces_off_by_default(minimal_payload) -> None:
    results = OracleEngine(minimal_payload, seed=19).run()
    assert results.traces is None
