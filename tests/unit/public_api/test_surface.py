"""Lock the curated public import surface (mirrors the reference's
__all__-equality tests, tests/unit/public_api/test_import.py)."""

import asyncflow_tpu
import asyncflow_tpu.analysis as analysis
import asyncflow_tpu.components as components
import asyncflow_tpu.enums as enums
import asyncflow_tpu.parallel as parallel
import asyncflow_tpu.settings as settings
import asyncflow_tpu.workload as workload


def test_top_level_surface() -> None:
    assert set(asyncflow_tpu.__all__) == {
        "AsyncFlow",
        "SimulationRunner",
        "TelemetryConfig",
        "__version__",
    }
    assert asyncflow_tpu.AsyncFlow is not None
    assert asyncflow_tpu.SimulationRunner is not None
    assert asyncflow_tpu.TelemetryConfig is not None
    assert isinstance(asyncflow_tpu.__version__, str)


def test_observability_surface() -> None:
    import asyncflow_tpu.observability as observability

    assert {
        "TelemetryConfig",
        "RunTelemetry",
        "CompileLedger",
        "PhaseTimer",
        "validate_run_record",
        "write_chrome_trace",
    } <= set(observability.__all__)


def test_components_surface() -> None:
    assert set(components.__all__) == {
        "CircuitBreaker",
        "Client",
        "Edge",
        "Endpoint",
        "EventInjection",
        "LoadBalancer",
        "OverloadPolicy",
        "Server",
        "ServerResources",
        "Step",
    }


def test_settings_surface() -> None:
    assert set(settings.__all__) == {"SimulationSettings"}


def test_workload_surface() -> None:
    assert set(workload.__all__) == {"RVConfig", "RqsGenerator"}


def test_analysis_surface() -> None:
    assert set(analysis.__all__) == {
        # legacy single-run analyzer
        "ResultsAnalyzer",
        # experiment design (also re-exported from asyncflow_tpu.schemas)
        "ExperimentConfig",
        "PrecisionTarget",
        "VarianceReduction",
        # interval estimators
        "IntervalEstimate",
        "binomial_rank_bounds",
        "pooled_quantile_ci",
        "bootstrap_mean_ci",
        "bootstrap_quantile_ci",
        "bootstrap_ratio_ci",
        "paired_delta_quantile_ci",
        "paired_delta_ratio_ci",
        "interval_for_metric",
        "paired_delta_for_metric",
        # host-fault quarantine: drop masked rows, note the exclusion
        "effective_results",
        # variance reduction helpers
        "antithetic_mean_ci",
        "antithetic_pair_means",
        "coupling_diagnostics",
        # A/B comparison + adaptive sequential sweeps
        "compare",
        "ComparisonReport",
        "AdaptiveSweep",
        "AdaptiveReport",
        "AdaptiveRound",
    }
    for name in analysis.__all__:
        assert getattr(analysis, name) is not None


def test_schemas_export_experiment_config() -> None:
    import asyncflow_tpu.schemas as schemas

    assert {
        "ExperimentConfig",
        "PrecisionTarget",
        "VarianceReduction",
    } <= set(schemas.__all__)


def test_parallel_surface() -> None:
    assert set(parallel.__all__) == {
        "SweepReport",
        "SweepRunner",
        "initialize_multihost",
        "make_overrides",
        "run_multihost_sweep",
        "scenario_mesh",
        "scenario_sharding",
        # host-fault recovery (docs/guides/fault-tolerance.md)
        "PREEMPTED_EXIT_CODE",
        "CorruptChunkError",
        "RecoveryPolicy",
        "RecoveryReport",
        "SweepPreempted",
        "read_manifest",
    }


def test_enums_cover_the_contract() -> None:
    expected = {
        "AggregatedMetricName",
        "Backend",
        "Distribution",
        "EndpointStepCPU",
        "EndpointStepIO",
        "EndpointStepRAM",
        "EventDescription",
        "EventMetricName",
        "LatencyKey",
        "LbAlgorithmsName",
        "SampledMetricName",
        "SamplePeriods",
        "ServerResourceName",
        "StepOperation",
        "SystemEdges",
        "SystemNodes",
        "TimeDefaults",
    }
    assert set(enums.__all__) == expected


def test_yaml_string_contract_is_stable() -> None:
    """Enum values are the on-disk format; renaming any is a breaking change."""
    assert enums.Distribution.LOG_NORMAL.value == "log_normal"
    assert enums.EndpointStepCPU.INITIAL_PARSING.value == "initial_parsing"
    assert enums.EndpointStepIO.WAIT.value == "io_wait"
    assert enums.EndpointStepRAM.RAM.value == "ram"
    assert enums.StepOperation.NECESSARY_RAM.value == "necessary_ram"
    assert enums.LbAlgorithmsName.LEAST_CONNECTIONS.value == "least_connection"
    assert enums.EventDescription.NETWORK_SPIKE_START.value == "network_spike_start"
    assert enums.SampledMetricName.EVENT_LOOP_IO_SLEEP.value == "event_loop_io_sleep"
    assert enums.EventMetricName.RQS_CLOCK.value == "rqs_clock"
    assert enums.LatencyKey.STD_DEV.value == "std_dev"


def test_checker_surface() -> None:
    import asyncflow_tpu.checker as checker

    assert set(checker.__all__) == {
        "ENGINE_OPTION_SUPPORT",
        "FENCES",
        "PREFLIGHT_MODES",
        "CheckReport",
        "Diagnostic",
        "Fence",
        "PreflightError",
        "PreflightWarning",
        "RoutingPrediction",
        "Severity",
        "TrippedFence",
        "check_payload",
        "fence_message",
        "predict_routing",
        "raise_fence",
        "run_preflight",
        "tripped_fences",
    }
    # the lazy check_payload attr resolves
    assert callable(checker.check_payload)
