"""Unit tests for Step/Endpoint coherence validators."""

import pytest
from pydantic import ValidationError

from asyncflow_tpu.schemas.endpoint import Endpoint, Step


def test_cpu_step_requires_cpu_time() -> None:
    step = Step(kind="initial_parsing", step_operation={"cpu_time": 0.002})
    assert step.is_cpu
    assert step.quantity == 0.002


def test_ram_step_requires_necessary_ram() -> None:
    step = Step(kind="ram", step_operation={"necessary_ram": 128})
    assert step.is_ram
    assert step.quantity == 128.0


def test_io_step_requires_io_waiting_time() -> None:
    step = Step(kind="io_db", step_operation={"io_waiting_time": 0.01})
    assert step.is_io


@pytest.mark.parametrize(
    ("kind", "operation"),
    [
        ("initial_parsing", {"io_waiting_time": 0.1}),
        ("initial_parsing", {"necessary_ram": 10}),
        ("ram", {"cpu_time": 0.1}),
        ("ram", {"io_waiting_time": 0.1}),
        ("io_wait", {"cpu_time": 0.1}),
        ("io_wait", {"necessary_ram": 10}),
    ],
)
def test_mismatched_kind_operation_rejected(kind: str, operation: dict) -> None:
    with pytest.raises(ValidationError):
        Step(kind=kind, step_operation=operation)


def test_empty_operation_rejected() -> None:
    with pytest.raises(ValidationError):
        Step(kind="initial_parsing", step_operation={})


def test_multiple_operations_rejected() -> None:
    with pytest.raises(ValidationError):
        Step(
            kind="initial_parsing",
            step_operation={"cpu_time": 0.1, "io_waiting_time": 0.1},
        )


def test_non_positive_quantity_rejected() -> None:
    with pytest.raises(ValidationError):
        Step(kind="initial_parsing", step_operation={"cpu_time": 0.0})


def test_unknown_kind_rejected() -> None:
    with pytest.raises(ValidationError):
        Step(kind="gpu_burn", step_operation={"cpu_time": 0.1})


def test_endpoint_name_lowercased() -> None:
    ep = Endpoint(
        endpoint_name="/API",
        steps=[Step(kind="initial_parsing", step_operation={"cpu_time": 0.1})],
    )
    assert ep.endpoint_name == "/api"
