"""Unit tests for event-injection schemas."""

import pytest
from pydantic import ValidationError

from asyncflow_tpu.config.constants import EventDescription
from asyncflow_tpu.schemas.events import End, EventInjection, Start


def _outage(eid: str = "ev-1", t0: float = 1.0, t1: float = 2.0) -> EventInjection:
    return EventInjection(
        event_id=eid,
        target_id="srv-1",
        start=Start(kind=EventDescription.SERVER_DOWN, t_start=t0),
        end=End(kind=EventDescription.SERVER_UP, t_end=t1),
    )


def _spike(
    eid: str = "ev-1",
    t0: float = 1.0,
    t1: float = 2.0,
    spike: float | None = 0.05,
) -> EventInjection:
    return EventInjection(
        event_id=eid,
        target_id="edge-1",
        start=Start(
            kind=EventDescription.NETWORK_SPIKE_START,
            t_start=t0,
            spike_s=spike,
        ),
        end=End(kind=EventDescription.NETWORK_SPIKE_END, t_end=t1),
    )


def test_valid_outage_and_spike() -> None:
    assert _outage().start.kind == EventDescription.SERVER_DOWN
    assert _spike().start.spike_s == 0.05


def test_mismatched_start_end_kind_rejected() -> None:
    with pytest.raises(ValidationError):
        EventInjection(
            event_id="ev",
            target_id="srv-1",
            start=Start(kind=EventDescription.SERVER_DOWN, t_start=0.0),
            end=End(kind=EventDescription.NETWORK_SPIKE_END, t_end=1.0),
        )


def test_start_after_end_rejected() -> None:
    with pytest.raises(ValidationError):
        _outage(t0=2.0, t1=1.0)
    with pytest.raises(ValidationError):
        _outage(t0=2.0, t1=2.0)


def test_spike_requires_spike_s() -> None:
    with pytest.raises(ValidationError):
        _spike(spike=None)


def test_outage_forbids_spike_s() -> None:
    with pytest.raises(ValidationError):
        EventInjection(
            event_id="ev",
            target_id="srv-1",
            start=Start(
                kind=EventDescription.SERVER_DOWN,
                t_start=0.0,
                spike_s=0.1,
            ),
            end=End(kind=EventDescription.SERVER_UP, t_end=1.0),
        )


def test_markers_frozen_and_strict() -> None:
    start = Start(kind=EventDescription.SERVER_DOWN, t_start=0.0)
    with pytest.raises(ValidationError):
        start.t_start = 5.0
    with pytest.raises(ValidationError):
        Start(kind=EventDescription.SERVER_DOWN, t_strat=0.0)


def test_negative_start_rejected() -> None:
    with pytest.raises(ValidationError):
        Start(kind=EventDescription.SERVER_DOWN, t_start=-1.0)
