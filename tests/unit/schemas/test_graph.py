"""Unit tests for TopologyGraph consistency validators."""

import pytest
from pydantic import ValidationError

from asyncflow_tpu.schemas.edges import Edge
from asyncflow_tpu.schemas.graph import TopologyGraph
from asyncflow_tpu.schemas.nodes import (
    Client,
    LoadBalancer,
    Server,
    ServerResources,
    TopologyNodes,
)
from asyncflow_tpu.schemas.random_variables import RVConfig


def _edge(eid: str, src: str, dst: str) -> Edge:
    return Edge(
        id=eid,
        source=src,
        target=dst,
        latency=RVConfig(mean=0.01, distribution="exponential"),
    )


def _server(sid: str) -> Server:
    return Server(id=sid, server_resources=ServerResources(), endpoints=[])


def _nodes(*server_ids: str, lb: LoadBalancer | None = None) -> TopologyNodes:
    return TopologyNodes(
        servers=[_server(s) for s in server_ids],
        client=Client(id="client-1"),
        load_balancer=lb,
    )


def test_valid_minimal_graph() -> None:
    graph = TopologyGraph(
        nodes=_nodes("srv-1"),
        edges=[
            _edge("g-c", "rqs-1", "client-1"),
            _edge("c-s", "client-1", "srv-1"),
            _edge("s-c", "srv-1", "client-1"),
        ],
    )
    assert graph.declared_node_ids() == {"srv-1", "client-1"}


def test_duplicate_edge_ids_rejected() -> None:
    with pytest.raises(ValidationError, match="multiple edges"):
        TopologyGraph(
            nodes=_nodes("srv-1"),
            edges=[
                _edge("dup", "client-1", "srv-1"),
                _edge("dup", "srv-1", "client-1"),
            ],
        )


def test_unknown_target_rejected() -> None:
    with pytest.raises(ValidationError, match="unknown target"):
        TopologyGraph(
            nodes=_nodes("srv-1"),
            edges=[_edge("e", "client-1", "ghost")],
        )


def test_external_source_as_target_rejected() -> None:
    # The unknown-target rule already covers external ids appearing as targets.
    with pytest.raises(ValidationError, match="unknown target"):
        TopologyGraph(
            nodes=_nodes("srv-1"),
            edges=[
                _edge("g-c", "rqs-1", "client-1"),
                _edge("s-g", "srv-1", "rqs-1"),
            ],
        )


def test_lb_covering_unknown_server_rejected() -> None:
    lb = LoadBalancer(id="lb-1", server_covered={"srv-1", "ghost"})
    with pytest.raises(ValidationError, match="unknown servers"):
        TopologyGraph(
            nodes=_nodes("srv-1", lb=lb),
            edges=[
                _edge("c-lb", "client-1", "lb-1"),
                _edge("lb-s1", "lb-1", "srv-1"),
                _edge("s1-c", "srv-1", "client-1"),
            ],
        )


def test_lb_covered_server_without_edge_rejected() -> None:
    lb = LoadBalancer(id="lb-1", server_covered={"srv-1", "srv-2"})
    with pytest.raises(ValidationError, match="no outgoing edge"):
        TopologyGraph(
            nodes=_nodes("srv-1", "srv-2", lb=lb),
            edges=[
                _edge("c-lb", "client-1", "lb-1"),
                _edge("lb-s1", "lb-1", "srv-1"),
                _edge("s1-c", "srv-1", "client-1"),
                _edge("s2-c", "srv-2", "client-1"),
            ],
        )


def test_fanout_from_non_lb_rejected() -> None:
    with pytest.raises(ValidationError, match="Only the load balancer"):
        TopologyGraph(
            nodes=_nodes("srv-1", "srv-2"),
            edges=[
                _edge("c-s1", "client-1", "srv-1"),
                _edge("c-s2", "client-1", "srv-2"),
                _edge("s1-c", "srv-1", "client-1"),
                _edge("s2-c", "srv-2", "client-1"),
            ],
        )


def test_lb_fanout_allowed() -> None:
    lb = LoadBalancer(id="lb-1", server_covered={"srv-1", "srv-2"})
    graph = TopologyGraph(
        nodes=_nodes("srv-1", "srv-2", lb=lb),
        edges=[
            _edge("g-c", "rqs-1", "client-1"),
            _edge("c-lb", "client-1", "lb-1"),
            _edge("lb-s1", "lb-1", "srv-1"),
            _edge("lb-s2", "lb-1", "srv-2"),
            _edge("s1-c", "srv-1", "client-1"),
            _edge("s2-c", "srv-2", "client-1"),
        ],
    )
    assert len(graph.edges) == 6
