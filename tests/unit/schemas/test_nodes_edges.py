"""Unit tests for node and edge schemas."""

import pytest
from pydantic import ValidationError

from asyncflow_tpu.config.constants import LbAlgorithmsName, SystemNodes
from asyncflow_tpu.schemas.edges import Edge
from asyncflow_tpu.schemas.nodes import (
    Client,
    LoadBalancer,
    Server,
    ServerResources,
    TopologyNodes,
)
from asyncflow_tpu.schemas.random_variables import RVConfig


def _server(sid: str = "srv-1") -> Server:
    return Server(
        id=sid,
        server_resources=ServerResources(),
        endpoints=[],
    )


class TestNodes:
    def test_client_type_fixed(self) -> None:
        assert Client(id="c").type == SystemNodes.CLIENT
        with pytest.raises(ValidationError):
            Client(id="c", type=SystemNodes.SERVER)

    def test_server_resources_defaults(self) -> None:
        res = ServerResources()
        assert res.cpu_cores == 1
        assert res.ram_mb == 1024
        assert res.db_connection_pool is None

    def test_server_resources_minima(self) -> None:
        with pytest.raises(ValidationError):
            ServerResources(cpu_cores=0)
        with pytest.raises(ValidationError):
            ServerResources(ram_mb=128)

    def test_lb_defaults(self) -> None:
        lb = LoadBalancer(id="lb-1")
        assert lb.algorithms == LbAlgorithmsName.ROUND_ROBIN
        assert lb.server_covered == set()

    def test_duplicate_node_ids_rejected(self) -> None:
        with pytest.raises(ValidationError):
            TopologyNodes(
                servers=[_server("x"), _server("x")],
                client=Client(id="c"),
            )
        with pytest.raises(ValidationError):
            TopologyNodes(servers=[_server("c")], client=Client(id="c"))

    def test_extra_fields_forbidden(self) -> None:
        with pytest.raises(ValidationError):
            TopologyNodes(
                servers=[_server()],
                client=Client(id="c"),
                router="nope",
            )


class TestEdges:
    def _edge(self, **overrides) -> Edge:
        base = {
            "id": "e-1",
            "source": "a",
            "target": "b",
            "latency": RVConfig(mean=0.01, distribution="exponential"),
        }
        base.update(overrides)
        return Edge(**base)

    def test_default_dropout(self) -> None:
        assert self._edge().dropout_rate == 0.01

    def test_dropout_bounds(self) -> None:
        with pytest.raises(ValidationError):
            self._edge(dropout_rate=-0.1)
        with pytest.raises(ValidationError):
            self._edge(dropout_rate=1.5)

    def test_self_loop_rejected(self) -> None:
        with pytest.raises(ValidationError):
            self._edge(source="a", target="a")

    def test_non_positive_latency_mean_rejected(self) -> None:
        with pytest.raises(ValidationError):
            self._edge(latency=RVConfig(mean=0.0, distribution="exponential"))

    def test_negative_variance_rejected(self) -> None:
        with pytest.raises(ValidationError):
            self._edge(
                latency=RVConfig(mean=1.0, distribution="normal", variance=-1.0),
            )
