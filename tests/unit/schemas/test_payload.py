"""Unit tests for SimulationPayload cross-cutting validators."""

import pytest
from pydantic import ValidationError

from asyncflow_tpu.config.constants import EventDescription
from asyncflow_tpu.schemas.events import End, EventInjection, Start
from asyncflow_tpu.schemas.payload import SimulationPayload


def _outage(eid: str, target: str, t0: float, t1: float) -> EventInjection:
    return EventInjection(
        event_id=eid,
        target_id=target,
        start=Start(kind=EventDescription.SERVER_DOWN, t_start=t0),
        end=End(kind=EventDescription.SERVER_UP, t_end=t1),
    )


def _spike(eid: str, target: str, t0: float, t1: float) -> EventInjection:
    return EventInjection(
        event_id=eid,
        target_id=target,
        start=Start(
            kind=EventDescription.NETWORK_SPIKE_START,
            t_start=t0,
            spike_s=0.05,
        ),
        end=End(kind=EventDescription.NETWORK_SPIKE_END, t_end=t1),
    )


def _with_events(minimal_payload: SimulationPayload, events) -> SimulationPayload:
    data = minimal_payload.model_dump()
    data["events"] = [e.model_dump() for e in events]
    return SimulationPayload.model_validate(data)


def test_payload_without_events_valid(minimal_payload: SimulationPayload) -> None:
    assert minimal_payload.events is None


def test_valid_events_accepted(minimal_payload: SimulationPayload) -> None:
    payload = _with_events(
        minimal_payload,
        [_spike("ev-1", "client-srv", 1.0, 5.0)],
    )
    assert payload.events is not None
    assert len(payload.events) == 1


def test_duplicate_event_ids_rejected(minimal_payload: SimulationPayload) -> None:
    with pytest.raises(ValidationError, match="unique"):
        _with_events(
            minimal_payload,
            [
                _spike("ev-1", "client-srv", 1.0, 5.0),
                _spike("ev-1", "srv-client", 2.0, 6.0),
            ],
        )


def test_unknown_event_target_rejected(minimal_payload: SimulationPayload) -> None:
    with pytest.raises(ValidationError, match="does not exist"):
        _with_events(minimal_payload, [_spike("ev-1", "ghost-edge", 1.0, 5.0)])


def test_event_outside_horizon_rejected(minimal_payload: SimulationPayload) -> None:
    horizon = minimal_payload.sim_settings.total_simulation_time
    with pytest.raises(ValidationError, match="horizon"):
        _with_events(
            minimal_payload,
            [_spike("ev-1", "client-srv", 1.0, horizon + 10.0)],
        )
    with pytest.raises(ValidationError, match="horizon"):
        _with_events(
            minimal_payload,
            [_spike("ev-1", "client-srv", horizon + 1.0, horizon + 2.0)],
        )


def test_server_event_on_edge_rejected(minimal_payload: SimulationPayload) -> None:
    with pytest.raises(ValidationError):
        _with_events(minimal_payload, [_outage("ev-1", "client-srv", 1.0, 5.0)])


def test_spike_event_on_server_rejected(minimal_payload: SimulationPayload) -> None:
    with pytest.raises(ValidationError):
        _with_events(minimal_payload, [_spike("ev-1", "srv-1", 1.0, 5.0)])


def test_all_servers_down_rejected(minimal_payload: SimulationPayload) -> None:
    # single-server topology: any outage would take all servers down
    with pytest.raises(ValidationError, match="all servers are down"):
        _with_events(minimal_payload, [_outage("ev-1", "srv-1", 1.0, 5.0)])


def test_overlapping_outages_rejected(minimal_payload: SimulationPayload) -> None:
    # single-server topology: the all-down sweep fires first here
    with pytest.raises(ValidationError):
        _with_events(
            minimal_payload,
            [
                _outage("ev-1", "srv-1", 1.0, 5.0),
                _outage("ev-2", "srv-1", 3.0, 8.0),
            ],
        )


def _add_second_server(minimal_payload: SimulationPayload) -> dict:
    data = minimal_payload.model_dump()
    srv2 = dict(data["topology_graph"]["nodes"]["servers"][0], id="srv-2")
    data["topology_graph"]["nodes"]["servers"].append(srv2)
    return data


def test_overlapping_outages_rejected_two_servers(minimal_payload) -> None:
    """With a second server up, the overlap validator itself must fire."""
    data = _add_second_server(minimal_payload)
    data["events"] = [
        _outage("ev-1", "srv-1", 1.0, 5.0).model_dump(),
        _outage("ev-2", "srv-1", 3.0, 8.0).model_dump(),
    ]
    with pytest.raises(ValidationError, match="must not overlap"):
        SimulationPayload.model_validate(data)


def test_spike_on_edge_named_like_server_not_an_outage(minimal_payload) -> None:
    """An edge id colliding with a server id must not turn spikes into outages."""
    data = minimal_payload.model_dump()
    # rename an edge to collide with the (single) server id
    data["topology_graph"]["edges"][1]["id"] = "srv-1"
    data["events"] = [_spike("ev-1", "srv-1", 1.0, 5.0).model_dump()]
    payload = SimulationPayload.model_validate(data)
    assert payload.events is not None


def test_back_to_back_outages_allowed_two_servers(minimal_payload) -> None:
    """END at t and START at t on the same server is legal (END sorts first)."""
    data = _add_second_server(minimal_payload)
    # srv-2 unreachable by edges is fine for schema-level validation
    data["events"] = [
        _outage("ev-1", "srv-1", 1.0, 5.0).model_dump(),
        _outage("ev-2", "srv-1", 5.0, 8.0).model_dump(),
    ]
    payload = SimulationPayload.model_validate(data)
    assert payload.events is not None
    assert len(payload.events) == 2
