"""Unit tests for RVConfig."""

import pytest
from pydantic import ValidationError

from asyncflow_tpu.config.constants import Distribution
from asyncflow_tpu.schemas.random_variables import RVConfig


def test_default_distribution_is_poisson() -> None:
    rv = RVConfig(mean=3.0)
    assert rv.distribution == Distribution.POISSON
    assert rv.variance is None


@pytest.mark.parametrize("dist", [Distribution.NORMAL, Distribution.LOG_NORMAL])
def test_variance_defaults_to_mean_when_needed(dist: Distribution) -> None:
    rv = RVConfig(mean=5.0, distribution=dist)
    assert rv.variance == 5.0


@pytest.mark.parametrize(
    "dist",
    [Distribution.POISSON, Distribution.EXPONENTIAL, Distribution.UNIFORM],
)
def test_variance_stays_none_otherwise(dist: Distribution) -> None:
    assert RVConfig(mean=5.0, distribution=dist).variance is None


def test_explicit_variance_is_kept() -> None:
    rv = RVConfig(mean=5.0, distribution=Distribution.NORMAL, variance=2.0)
    assert rv.variance == 2.0


@pytest.mark.parametrize("bad", ["three", None, [1], {"m": 1}, True])
def test_non_numeric_mean_rejected(bad: object) -> None:
    with pytest.raises(ValidationError):
        RVConfig(mean=bad)


def test_int_mean_coerced_to_float() -> None:
    rv = RVConfig(mean=4)
    assert isinstance(rv.mean, float)
    assert rv.mean == 4.0


def test_unknown_distribution_rejected() -> None:
    with pytest.raises(ValidationError):
        RVConfig(mean=1.0, distribution="zipf")
