"""Validation contracts of the resilience schemas (retry + faults)."""

from __future__ import annotations

import pytest
import yaml
from pydantic import ValidationError

from asyncflow_tpu.schemas.payload import SimulationPayload
from asyncflow_tpu.schemas.resilience import FaultEvent, FaultTimeline, RetryPolicy

BASE = "tests/integration/data/single_server.yml"


def _data():
    return yaml.safe_load(open(BASE).read())


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_retry_policy_defaults_and_backoff_schedule() -> None:
    policy = RetryPolicy(request_timeout_s=1.0)
    assert policy.max_attempts == 3
    assert policy.budget_tokens is None  # unlimited by default
    p = RetryPolicy(
        request_timeout_s=1.0,
        backoff_base_s=0.1,
        backoff_multiplier=2.0,
        backoff_cap_s=0.35,
        max_attempts=5,
    )
    # attempt 2 = first retry -> base; growth is capped
    assert p.backoff_delay(2) == pytest.approx(0.1)
    assert p.backoff_delay(3) == pytest.approx(0.2)
    assert p.backoff_delay(4) == pytest.approx(0.35)
    assert p.backoff_delay(5) == pytest.approx(0.35)


def test_retry_policy_bounds() -> None:
    with pytest.raises(ValidationError):
        RetryPolicy(request_timeout_s=0.0)
    with pytest.raises(ValidationError):
        RetryPolicy(request_timeout_s=1.0, max_attempts=0)
    with pytest.raises(ValidationError):
        RetryPolicy(request_timeout_s=1.0, max_attempts=17)  # > cap
    with pytest.raises(ValidationError):
        RetryPolicy(request_timeout_s=1.0, jitter=1.5)
    with pytest.raises(ValidationError):
        RetryPolicy(request_timeout_s=1.0, backoff_multiplier=0.5)


# ---------------------------------------------------------------------------
# FaultEvent / FaultTimeline
# ---------------------------------------------------------------------------


def test_fault_event_window_and_field_consistency() -> None:
    with pytest.raises(ValidationError, match="smaller than t_end"):
        FaultEvent(
            fault_id="f",
            kind="server_outage",
            target_id="s",
            t_start=5.0,
            t_end=5.0,
        )
    with pytest.raises(ValidationError, match="only to edge_degrade"):
        FaultEvent(
            fault_id="f",
            kind="server_outage",
            target_id="s",
            t_start=0.0,
            t_end=1.0,
            latency_factor=2.0,
        )
    with pytest.raises(ValidationError, match="needs"):
        FaultEvent(
            fault_id="f",
            kind="edge_degrade",
            target_id="e",
            t_start=0.0,
            t_end=1.0,
        )
    ok = FaultEvent(
        fault_id="f",
        kind="edge_degrade",
        target_id="e",
        t_start=0.0,
        t_end=1.0,
        latency_factor=3.0,
        dropout_boost=0.2,
    )
    assert ok.latency_factor == 3.0


def test_fault_timeline_unique_ids() -> None:
    event = {
        "fault_id": "dup",
        "kind": "server_outage",
        "target_id": "s",
        "t_start": 0.0,
        "t_end": 1.0,
    }
    with pytest.raises(ValidationError, match="duplicate fault ids"):
        FaultTimeline(events=[event, dict(event)])


# ---------------------------------------------------------------------------
# payload cross-validators
# ---------------------------------------------------------------------------


def test_fault_target_must_exist_and_match_kind() -> None:
    data = _data()
    data["fault_timeline"] = {
        "events": [
            {
                "fault_id": "f",
                "kind": "server_outage",
                "target_id": "no-such-server",
                "t_start": 0.0,
                "t_end": 1.0,
            },
        ],
    }
    with pytest.raises(ValidationError, match="not a declared server"):
        SimulationPayload.model_validate(data)
    data["fault_timeline"]["events"][0]["kind"] = "edge_partition"
    with pytest.raises(ValidationError, match="not a declared edge"):
        SimulationPayload.model_validate(data)


def test_fault_window_inside_horizon() -> None:
    data = _data()
    horizon = float(data["sim_settings"]["total_simulation_time"])
    data["fault_timeline"] = {
        "events": [
            {
                "fault_id": "f",
                "kind": "server_outage",
                "target_id": "srv-1",
                "t_start": 0.0,
                "t_end": horizon + 1.0,
            },
        ],
    }
    with pytest.raises(ValidationError, match="exceeds the"):
        SimulationPayload.model_validate(data)


def test_retry_policy_refused_with_multiple_generators() -> None:
    data = _data()
    gen = dict(data["rqs_input"])
    gen2 = dict(gen)
    gen2["id"] = "rqs-2"
    data["rqs_input"] = [gen, gen2]
    # give the second generator its own entry edge
    data["topology_graph"]["edges"].append(
        {
            "id": "gen2-client",
            "source": "rqs-2",
            "target": data["topology_graph"]["nodes"]["client"]["id"],
            "latency": {"mean": 0.003, "distribution": "exponential"},
        },
    )
    data["retry_policy"] = {"request_timeout_s": 1.0}
    with pytest.raises(ValidationError, match="multiple generators"):
        SimulationPayload.model_validate(data)
