"""Cross-scenario gauge quantile bands (``SweepResults.gauge_bands``).

Chunks reduce their coarse gauge series into fixed-bin value histograms
(``gauge_hist``) that sum across chunk rows; bands are read back through the
repo's one percentile definition (``hist_percentile``).  The histograms must
be rebuilt — never row-sliced — on every scenario-axis edit, persist through
checkpoint resume, and exclude quarantined rows.
"""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.engines.results import (
    GAUGE_BAND_QS,
    GAUGE_HIST_BINS,
    build_gauge_hist,
    gauge_hist_caps,
)
from asyncflow_tpu.parallel import SweepRunner
from asyncflow_tpu.parallel.recovery import _zero_rows
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"
SPEC = ("ram_in_use", ["srv-1"], 1.0)


def _payload(horizon: int = 60) -> SimulationPayload:
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    return SimulationPayload.model_validate(data)


def test_bands_match_inverted_cdf_within_one_bin() -> None:
    rep = SweepRunner(_payload(), use_mesh=False, gauge_series=SPEC).run(
        16, seed=7, chunk_size=4,
    )
    res = rep.results
    assert res.gauge_hist.shape == (
        res.gauge_series.shape[1],
        1,
        GAUGE_HIST_BINS,
    )
    # every (tick, column) cell pools exactly the effective scenario count
    assert np.all(res.gauge_hist.sum(axis=-1) == 16)
    # ram columns are capped by the server's ram_mb
    assert res.gauge_hist_cap[0] == pytest.approx(1024.0)

    bands = res.gauge_bands
    assert bands.shape == (len(GAUGE_BAND_QS), res.gauge_series.shape[1], 1)
    assert np.all(bands[0] <= bands[1] + 1e-9)
    assert np.all(bands[1] <= bands[2] + 1e-9)
    # hist_percentile interpolates inside the crossing bin, so it sits
    # within one bin width of the inverted-CDF sample percentile
    binw = res.gauge_hist_cap[0] / GAUGE_HIST_BINS
    exact = np.percentile(
        res.gauge_series[:, :, 0],
        list(GAUGE_BAND_QS),
        axis=0,
        method="inverted_cdf",
    )
    assert np.abs(bands[:, :, 0] - exact).max() <= binw + 1e-9

    # the report accessor selects the component column
    times, b = rep.gauge_bands("srv-1")
    assert b.shape == (len(GAUGE_BAND_QS), res.gauge_series.shape[1])
    np.testing.assert_array_equal(b, bands[:, :, 0])
    assert times[0] == pytest.approx(SPEC[2])


def test_chunks_sum_to_single_chunk_hist() -> None:
    # the chunked run's summed histograms must equal one big chunk's
    payload = _payload()
    chunked = SweepRunner(payload, use_mesh=False, gauge_series=SPEC).run(
        8, seed=3, chunk_size=2,
    )
    whole = SweepRunner(payload, use_mesh=False, gauge_series=SPEC).run(
        8, seed=3, chunk_size=8,
    )
    np.testing.assert_array_equal(
        chunked.results.gauge_hist, whole.results.gauge_hist,
    )


def test_event_engine_records_band_histograms() -> None:
    rep = SweepRunner(
        _payload(), engine="event", use_mesh=False, gauge_series=SPEC,
    ).run(4, seed=5, chunk_size=4)
    assert rep.results.gauge_hist is not None
    assert np.all(rep.results.gauge_hist.sum(axis=-1) == 4)
    assert rep.results.gauge_bands is not None


def test_hist_survives_checkpoint_resume(tmp_path) -> None:
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False, gauge_series=SPEC)
    first = runner.run(8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path))
    resumed = runner.run(8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path))
    np.testing.assert_array_equal(
        first.results.gauge_hist, resumed.results.gauge_hist,
    )
    np.testing.assert_array_equal(
        first.results.gauge_hist_cap, resumed.results.gauge_hist_cap,
    )


def test_scenario_slicing_rebuilds_hist() -> None:
    rep = SweepRunner(_payload(), use_mesh=False, gauge_series=SPEC).run(
        8, seed=9, chunk_size=8,
    )
    sliced = rep.results[:4]
    assert np.all(sliced.gauge_hist.sum(axis=-1) == 4)
    np.testing.assert_array_equal(
        sliced.gauge_hist,
        build_gauge_hist(rep.results.gauge_series[:4], sliced.gauge_hist_cap),
    )


def test_quarantined_rows_leave_the_bands() -> None:
    rep = SweepRunner(_payload(), use_mesh=False, gauge_series=SPEC).run(
        8, seed=9, chunk_size=8,
    )
    part = rep.results[:8]  # detached copy
    part = _zero_rows(part, [1, 5], ["host fault", "host fault"])
    # the masked rows are gone from the pooled counts...
    assert np.all(part.gauge_hist.sum(axis=-1) == 6)
    # ...and the remaining histogram is exactly the survivors'
    survivors = np.delete(rep.results.gauge_series, [1, 5], axis=0)
    np.testing.assert_array_equal(
        part.gauge_hist,
        build_gauge_hist(survivors, part.gauge_hist_cap),
    )


def test_caps_follow_gauge_layout() -> None:
    from asyncflow_tpu.compiler import compile_payload

    plan = compile_payload(_payload())
    sel = [plan.gauge_edge(0), plan.gauge_ready(0), plan.gauge_ram(0)]
    caps = gauge_hist_caps(plan, sel)
    assert caps[0] == pytest.approx(plan.pool_size)
    assert caps[1] == pytest.approx(plan.pool_size)
    assert caps[2] == pytest.approx(float(np.asarray(plan.server_ram)[0]))


def test_bands_absent_without_spec() -> None:
    rep = SweepRunner(_payload(), use_mesh=False).run(4, seed=1, chunk_size=4)
    assert rep.results.gauge_hist is None
    assert rep.results.gauge_bands is None
    with pytest.raises(ValueError, match="no streaming gauge series"):
        rep.gauge_bands("srv-1")
