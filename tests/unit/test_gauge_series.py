"""Streaming per-scenario gauge time series for sweeps.

The coarse-grid series must be exactly the fine-grid series sampled at the
coarse ticks (same interval-endpoint scatter rule on either grid), survive
the scanned execution shape and checkpoint round trips, and agree between
the scan fast path and the XLA event engine (the gauge_series.requires_fast
fence is burned; only pallas/native refuse)."""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.engines.jaxsim.engine import scenario_keys
from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine
from asyncflow_tpu.parallel import SweepRunner
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"
RESAMPLE_S = 1.0


def _payload(horizon: int = 60) -> SimulationPayload:
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    return SimulationPayload.model_validate(data)


def test_coarse_series_matches_fine_grid_at_ticks() -> None:
    payload = _payload()
    plan = compile_payload(payload)
    n = 4

    runner = SweepRunner(
        payload,
        use_mesh=False,
        gauge_series=("ram_in_use", ["srv-1"], RESAMPLE_S),
    )
    report = runner.run(n, seed=5, chunk_size=n)
    times, series = report.gauge_series("srv-1")
    assert series.shape[0] == n
    assert report.results.gauge_series_period == pytest.approx(RESAMPLE_S)
    assert times[0] == pytest.approx(RESAMPLE_S)
    assert series.max() > 0  # RAM is actually held in this scenario

    # exact fine-grid reference: same keys through the exact gauge grid
    exact_engine = FastEngine(plan, collect_gauges=True)
    final = exact_engine.run_batch(scenario_keys(5, n))
    fine = np.cumsum(np.asarray(final.gauge), axis=1)[:, 1 : plan.n_samples + 1]
    stride = round(RESAMPLE_S / plan.sample_period)
    ram_col = plan.gauge_ram(0)
    for i in range(series.shape[1]):
        np.testing.assert_allclose(
            series[:, i],
            fine[:, (i + 1) * stride - 1, ram_col],
            rtol=1e-5,
            atol=1e-4,
        )


def test_series_identical_scanned_vs_vmapped() -> None:
    payload = _payload()
    spec = ("edge_concurrent_connection", ["client-srv"], RESAMPLE_S)
    n = 8
    scanned = SweepRunner(
        payload, use_mesh=False, gauge_series=spec,
    ).run(n, seed=3, chunk_size=4)
    plain = SweepRunner(
        payload, use_mesh=False, gauge_series=spec, scan_inner=0,
    ).run(n, seed=3, chunk_size=8)
    np.testing.assert_allclose(
        scanned.results.gauge_series,
        plain.results.gauge_series,
        rtol=1e-6,
        atol=1e-5,
    )


def test_series_checkpoint_roundtrip(tmp_path) -> None:
    payload = _payload()
    spec = ("ready_queue_len", "srv-1", RESAMPLE_S)  # bare str component
    runner = SweepRunner(payload, use_mesh=False, gauge_series=spec)
    first = runner.run(8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path))
    resumed = runner.run(8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path))
    assert first.results.gauge_series is not None
    np.testing.assert_array_equal(
        first.results.gauge_series, resumed.results.gauge_series,
    )
    assert resumed.results.gauge_series_period == pytest.approx(RESAMPLE_S)

    # a sweep without the spec must not reuse those chunks
    other = SweepRunner(payload, use_mesh=False).run(
        8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path),
    )
    assert other.results.gauge_series is None


def test_confidence_intervals_and_bands() -> None:
    """Reference ROADMAP §3 deliverables: CIs on Monte-Carlo metrics and
    percentile bands over streamed time series."""
    payload = _payload()
    runner = SweepRunner(
        payload,
        use_mesh=False,
        gauge_series=("ram_in_use", ["srv-1"], RESAMPLE_S),
    )
    report = runner.run(32, seed=2, chunk_size=16)

    point, lo, hi = report.per_scenario_percentile_mean_ci(95)
    assert lo < point < hi
    assert np.isfinite(lo) and hi - lo < point  # a meaningful interval
    # wider confidence -> wider interval
    _, lo99, hi99 = report.per_scenario_percentile_mean_ci(95, level=0.99)
    assert hi99 - lo99 > hi - lo
    # the legacy name still answers, but warns about its misleading reading
    import pytest as _pytest

    with _pytest.warns(DeprecationWarning, match="per_scenario_percentile"):
        legacy = report.percentile_ci(95)
    assert legacy == (point, lo, hi)

    c_point, c_lo, c_hi = report.metric_ci(report.results.completed)
    assert c_lo < c_point < c_hi

    times, b_lo, b_med, b_hi = report.gauge_series_band("srv-1")
    assert times.shape == b_lo.shape == b_med.shape == b_hi.shape
    assert np.all(b_lo <= b_med + 1e-9) and np.all(b_med <= b_hi + 1e-9)

    import pytest as _pytest

    with _pytest.raises(ValueError, match="confidence level"):
        report.per_scenario_percentile_mean_ci(95, level=1.5)


def test_series_runs_on_the_event_engine() -> None:
    # gauge_series.requires_fast is burned: a poisson-edge plan (not
    # fastpath-eligible) auto-routes to the XLA event engine and still
    # streams the coarse series instead of refusing.
    data = yaml.safe_load(open(BASE).read())
    data["topology_graph"]["edges"][0]["latency"]["distribution"] = "poisson"
    data["sim_settings"]["total_simulation_time"] = 60
    payload = SimulationPayload.model_validate(data)
    runner = SweepRunner(
        payload,
        use_mesh=False,
        gauge_series=("ram_in_use", ["srv-1"], RESAMPLE_S),
    )
    assert runner.engine_kind == "event"
    assert not runner.plan.fastpath_ok
    report = runner.run(4, seed=5, chunk_size=4)
    times, series = report.gauge_series("srv-1")
    assert series.shape[0] == 4
    assert report.results.gauge_series_period == pytest.approx(RESAMPLE_S)
    assert series.max() > 0  # RAM is actually held in this scenario


def test_event_coarse_series_matches_event_fine_grid() -> None:
    """The event engine's coarse grid obeys the same resample contract as
    the fast path's: tick i reads exactly the fine-grid value at
    t=(i+1)*period (float32 gauge deltas are integral here, so cumsum on
    either grid is exact)."""
    from asyncflow_tpu.engines.jaxsim.engine import Engine

    payload = _payload()
    plan = compile_payload(payload)
    n = 4
    stride = round(RESAMPLE_S / plan.sample_period)
    keys = scenario_keys(5, n)
    coarse_final = Engine(plan, gauge_series_stride=stride).run_batch(keys)
    fine_final = Engine(plan, collect_gauges=True).run_batch(keys)
    coarse = np.cumsum(np.asarray(coarse_final.gauge), axis=1)[:, 1:-1]
    fine = np.cumsum(np.asarray(fine_final.gauge), axis=1)[
        :, 1 : plan.n_samples + 1,
    ]
    ram = plan.gauge_ram(0)
    assert coarse.shape[1] == plan.n_samples // stride
    assert np.any(coarse[:, :, ram] > 0)
    for i in range(coarse.shape[1]):
        np.testing.assert_array_equal(
            coarse[:, i, ram], fine[:, (i + 1) * stride - 1, ram],
        )


def test_fast_event_series_agree_on_saturating_plateau() -> None:
    """Cross-engine gate for the burned fence.  The two engines sample
    arrivals with structurally different constructions (incremental gaps
    vs per-window order statistics), so general series only agree
    distributionally — but a saturating RAM-hold plan pins both to the
    same deterministic plateau: io_wait longer than the horizon means
    every admitted request holds its 64 MB to the end, and at ~67
    arrivals/s the 16 grants that exhaust ram_mb=1024 all land before the
    first 1 s coarse tick w.p. 1 - P(Poisson(67) < 16) ~ 1-1e-12.  Every
    tick on both engines must then read exactly 1024."""
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = 10
    data["rqs_input"]["avg_active_users"]["mean"] = 200
    steps = data["topology_graph"]["nodes"]["servers"][0]["endpoints"][0][
        "steps"
    ]
    steps[2]["step_operation"]["io_waiting_time"] = 60.0
    payload = SimulationPayload.model_validate(data)
    assert compile_payload(payload).fastpath_ok
    spec = ("ram_in_use", ["srv-1"], RESAMPLE_S)
    series = {}
    for eng in ("fast", "event"):
        runner = SweepRunner(
            payload, engine=eng, use_mesh=False, gauge_series=spec,
            preflight="off",  # AF402: the saturation is the point
        )
        assert runner.engine_kind == eng
        _, series[eng] = runner.run(4, seed=11, chunk_size=4).gauge_series(
            "srv-1",
        )
    np.testing.assert_array_equal(series["fast"], series["event"])
    assert np.all(series["event"] == 1024.0)


def test_series_spec_validation() -> None:
    payload = _payload()
    with pytest.raises(ValueError, match="unknown server"):
        SweepRunner(
            payload,
            use_mesh=False,
            gauge_series=("ram_in_use", ["nope"], 1.0),
        )
    with pytest.raises(ValueError, match="tuple"):
        SweepRunner(payload, use_mesh=False, gauge_series=("ram_in_use",))
    # sub-sample_period resampling would silently allocate the full fine
    # grid per scenario — must be rejected, not clamped
    with pytest.raises(ValueError, match="finer than the sample period"):
        SweepRunner(
            payload,
            use_mesh=False,
            gauge_series=("ram_in_use", ["srv-1"], 0.0),
        )
