"""Streaming per-scenario gauge time series for sweeps.

The coarse-grid series must be exactly the fine-grid series sampled at the
coarse ticks (same interval-endpoint scatter rule on either grid), survive
the scanned execution shape and checkpoint round trips, and refuse plans
that don't run on the fast path.
"""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.engines.jaxsim.engine import scenario_keys
from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine
from asyncflow_tpu.parallel import SweepRunner
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"
RESAMPLE_S = 1.0


def _payload(horizon: int = 60) -> SimulationPayload:
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    return SimulationPayload.model_validate(data)


def test_coarse_series_matches_fine_grid_at_ticks() -> None:
    payload = _payload()
    plan = compile_payload(payload)
    n = 4

    runner = SweepRunner(
        payload,
        use_mesh=False,
        gauge_series=("ram_in_use", ["srv-1"], RESAMPLE_S),
    )
    report = runner.run(n, seed=5, chunk_size=n)
    times, series = report.gauge_series("srv-1")
    assert series.shape[0] == n
    assert report.results.gauge_series_period == pytest.approx(RESAMPLE_S)
    assert times[0] == pytest.approx(RESAMPLE_S)
    assert series.max() > 0  # RAM is actually held in this scenario

    # exact fine-grid reference: same keys through the exact gauge grid
    exact_engine = FastEngine(plan, collect_gauges=True)
    final = exact_engine.run_batch(scenario_keys(5, n))
    fine = np.cumsum(np.asarray(final.gauge), axis=1)[:, 1 : plan.n_samples + 1]
    stride = round(RESAMPLE_S / plan.sample_period)
    ram_col = plan.gauge_ram(0)
    for i in range(series.shape[1]):
        np.testing.assert_allclose(
            series[:, i],
            fine[:, (i + 1) * stride - 1, ram_col],
            rtol=1e-5,
            atol=1e-4,
        )


def test_series_identical_scanned_vs_vmapped() -> None:
    payload = _payload()
    spec = ("edge_concurrent_connection", ["client-srv"], RESAMPLE_S)
    n = 8
    scanned = SweepRunner(
        payload, use_mesh=False, gauge_series=spec,
    ).run(n, seed=3, chunk_size=4)
    plain = SweepRunner(
        payload, use_mesh=False, gauge_series=spec, scan_inner=0,
    ).run(n, seed=3, chunk_size=8)
    np.testing.assert_allclose(
        scanned.results.gauge_series,
        plain.results.gauge_series,
        rtol=1e-6,
        atol=1e-5,
    )


def test_series_checkpoint_roundtrip(tmp_path) -> None:
    payload = _payload()
    spec = ("ready_queue_len", "srv-1", RESAMPLE_S)  # bare str component
    runner = SweepRunner(payload, use_mesh=False, gauge_series=spec)
    first = runner.run(8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path))
    resumed = runner.run(8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path))
    assert first.results.gauge_series is not None
    np.testing.assert_array_equal(
        first.results.gauge_series, resumed.results.gauge_series,
    )
    assert resumed.results.gauge_series_period == pytest.approx(RESAMPLE_S)

    # a sweep without the spec must not reuse those chunks
    other = SweepRunner(payload, use_mesh=False).run(
        8, seed=9, chunk_size=4, checkpoint_dir=str(tmp_path),
    )
    assert other.results.gauge_series is None


def test_confidence_intervals_and_bands() -> None:
    """Reference ROADMAP §3 deliverables: CIs on Monte-Carlo metrics and
    percentile bands over streamed time series."""
    payload = _payload()
    runner = SweepRunner(
        payload,
        use_mesh=False,
        gauge_series=("ram_in_use", ["srv-1"], RESAMPLE_S),
    )
    report = runner.run(32, seed=2, chunk_size=16)

    point, lo, hi = report.per_scenario_percentile_mean_ci(95)
    assert lo < point < hi
    assert np.isfinite(lo) and hi - lo < point  # a meaningful interval
    # wider confidence -> wider interval
    _, lo99, hi99 = report.per_scenario_percentile_mean_ci(95, level=0.99)
    assert hi99 - lo99 > hi - lo
    # the legacy name still answers, but warns about its misleading reading
    import pytest as _pytest

    with _pytest.warns(DeprecationWarning, match="per_scenario_percentile"):
        legacy = report.percentile_ci(95)
    assert legacy == (point, lo, hi)

    c_point, c_lo, c_hi = report.metric_ci(report.results.completed)
    assert c_lo < c_point < c_hi

    times, b_lo, b_med, b_hi = report.gauge_series_band("srv-1")
    assert times.shape == b_lo.shape == b_med.shape == b_hi.shape
    assert np.all(b_lo <= b_med + 1e-9) and np.all(b_med <= b_hi + 1e-9)

    import pytest as _pytest

    with _pytest.raises(ValueError, match="confidence level"):
        report.per_scenario_percentile_mean_ci(95, level=1.5)


def test_series_requires_fast_path() -> None:
    data = yaml.safe_load(open(BASE).read())
    data["topology_graph"]["edges"][0]["latency"]["distribution"] = "poisson"
    data["sim_settings"]["total_simulation_time"] = 60
    payload = SimulationPayload.model_validate(data)
    with pytest.raises(ValueError, match="fast-path"):
        SweepRunner(
            payload,
            use_mesh=False,
            gauge_series=("ram_in_use", ["srv-1"], 1.0),
        )


def test_series_spec_validation() -> None:
    payload = _payload()
    with pytest.raises(ValueError, match="unknown server"):
        SweepRunner(
            payload,
            use_mesh=False,
            gauge_series=("ram_in_use", ["nope"], 1.0),
        )
    with pytest.raises(ValueError, match="tuple"):
        SweepRunner(payload, use_mesh=False, gauge_series=("ram_in_use",))
    # sub-sample_period resampling would silently allocate the full fine
    # grid per scenario — must be rejected, not clamped
    with pytest.raises(ValueError, match="finer than the sample period"):
        SweepRunner(
            payload,
            use_mesh=False,
            gauge_series=("ram_in_use", ["srv-1"], 0.0),
        )
