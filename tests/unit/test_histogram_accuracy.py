"""Histogram quantization: sweep percentiles must not eat the parity budget.

The sweep path estimates percentiles from 1024 log-spaced bins over
[1e-4, 1e3] s (~1.6% relative bin width) with linear interpolation inside
the crossing bin.  VERDICT r1 flagged that quantization alone could consume
most of a +/-2% p95 budget; this pins the actual error against exact clocks
computed on the same runs.
"""

from __future__ import annotations

import numpy as np
import pytest

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.engines.jaxsim.engine import scenario_keys, sweep_results
from asyncflow_tpu.engines.jaxsim.fastpath import FastEngine
from asyncflow_tpu.runtime.runner import SimulationRunner

pytestmark = pytest.mark.integration


def test_sweep_percentiles_match_exact_clocks() -> None:
    payload = SimulationRunner.from_yaml(
        "tests/integration/data/two_servers_lb.yml",
    ).simulation_input
    plan = compile_payload(payload)
    engine = FastEngine(plan, collect_clocks=True)
    n = 16
    final = engine.run_batch(scenario_keys(3, n))

    # exact pooled percentiles from the clock tables
    clock = np.asarray(final.clock)
    counts = np.asarray(final.clock_n)
    exact = np.concatenate(
        [clock[i, : counts[i], 1] - clock[i, : counts[i], 0] for i in range(n)],
    )

    # histogram-estimated pooled percentiles via the sweep reduction
    res = sweep_results(engine, final, payload.sim_settings)
    import dataclasses

    pooled = dataclasses.replace(
        res,
        latency_hist=res.latency_hist.sum(axis=0, keepdims=True),
    )
    for q in (50, 90, 95, 99):
        est = float(pooled.percentile(q)[0])
        ref = float(np.percentile(exact, q))
        rel = abs(est - ref) / ref
        assert rel < 0.01, f"p{q}: histogram={est:.6f} exact={ref:.6f} rel={rel:.4f}"


def test_per_scenario_percentiles_match_exact_clocks() -> None:
    payload = SimulationRunner.from_yaml(
        "tests/integration/data/single_server.yml",
    ).simulation_input
    plan = compile_payload(payload)
    engine = FastEngine(plan, collect_clocks=True)
    n = 8
    final = engine.run_batch(scenario_keys(4, n))
    res = sweep_results(engine, final, payload.sim_settings)
    est = res.percentile(95)

    clock = np.asarray(final.clock)
    counts = np.asarray(final.clock_n)
    for i in range(n):
        lat = clock[i, : counts[i], 1] - clock[i, : counts[i], 0]
        ref = float(np.percentile(lat, 95))
        rel = abs(float(est[i]) - ref) / ref
        assert rel < 0.02, f"scenario {i}: histogram={est[i]:.6f} exact={ref:.6f}"
