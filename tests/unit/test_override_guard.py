"""Sweep override guards: compile-time fast-path proofs must survive
per-scenario workload overrides or refuse them loudly."""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.parallel import SweepRunner, make_overrides
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"


def _multi_burst_payload(users: int) -> SimulationPayload:
    data = yaml.safe_load(open(BASE).read())
    server = data["topology_graph"]["nodes"]["servers"][0]
    server["endpoints"][0]["steps"] = [
        {"kind": "initial_parsing", "step_operation": {"cpu_time": 0.018}},
        {"kind": "io_wait", "step_operation": {"io_waiting_time": 0.015}},
        {"kind": "cpu_bound_operation", "step_operation": {"cpu_time": 0.012}},
    ]
    data["rqs_input"]["avg_active_users"]["mean"] = users
    data["sim_settings"]["total_simulation_time"] = 60
    return SimulationPayload.model_validate(data)


def test_envelope_guard_blocks_rate_raising_overrides() -> None:
    """Base rho ~ 0.5 is eligible; an override scaling users x1.6 would put
    the multi-burst server at rho ~ 0.8 — outside the measured relaxation
    envelope — and must be refused, not silently simulated with bias."""
    payload = _multi_burst_payload(50)  # rho = 50*20/60*0.03 = 0.50
    plan = compile_payload(payload)
    assert plan.fastpath_ok, plan.fastpath_reason
    assert plan.relax_rho == pytest.approx(0.50, abs=0.01)

    runner = SweepRunner(payload, use_mesh=False)
    n = 4
    bad = make_overrides(runner.plan, n, user_mean=np.full(n, 80.0))
    with pytest.raises(ValueError, match="validity envelope"):
        runner.run(n, seed=0, overrides=bad, chunk_size=n)


def test_envelope_guard_allows_inside_envelope_overrides() -> None:
    payload = _multi_burst_payload(50)
    runner = SweepRunner(payload, use_mesh=False)
    n = 4
    ok = make_overrides(runner.plan, n, user_mean=np.full(n, 65.0))  # rho 0.65
    report = runner.run(n, seed=0, overrides=ok, chunk_size=n)
    assert report.summary()["completed_total"] > 0


def test_envelope_guard_ignores_rate_lowering_overrides() -> None:
    payload = _multi_burst_payload(60)
    runner = SweepRunner(payload, use_mesh=False)
    n = 4
    down = make_overrides(runner.plan, n, user_mean=np.full(n, 20.0))
    report = runner.run(n, seed=0, overrides=down, chunk_size=n)
    assert report.summary()["completed_total"] > 0
