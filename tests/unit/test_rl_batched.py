"""Batched RL rollouts: the segmented event-engine API and the vector env.

The load-bearing invariant: stepping the engine to the horizon in windows
(``Engine.init_batch`` + ``run_until``) is BIT-IDENTICAL to one
``run_batch`` call — the rollout engine is the parity-tested event
engine, windows only pause its loop.  On top of that, weighted routing
(the action channel) must match the oracle's ``lb_weights`` hook
distributionally, and the vector env's rewards must agree with the
sequential oracle env under the same uniform policy.
"""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.compiler import compile_payload
from asyncflow_tpu.engines.jaxsim.engine import Engine, scenario_keys
from asyncflow_tpu.engines.oracle.engine import OracleEngine
from asyncflow_tpu.rl import BatchedLoadBalancerEnv, LoadBalancerEnv
from asyncflow_tpu.schemas.payload import SimulationPayload

LB = "examples/yaml_input/data/two_servers_lb.yml"


def _payload(horizon: float = 20.0) -> SimulationPayload:
    data = yaml.safe_load(open(LB).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    return SimulationPayload.model_validate(data)


def test_windowed_run_until_is_bit_identical() -> None:
    plan = compile_payload(_payload())
    eng = Engine(plan)
    keys = scenario_keys(7, 4)
    full = eng.run_batch(keys)
    st = eng.init_batch(keys)
    for stop in np.arange(4.0, 21.0, 4.0):
        st = eng.run_until(st, float(stop))
    import jax.numpy as jnp

    for f in full._fields:
        assert bool(
            jnp.all(jnp.asarray(getattr(full, f)) == jnp.asarray(getattr(st, f))),
        ), f


def test_weighted_routing_matches_oracle_split() -> None:
    """80/20 routing weights: the per-server completion split must match
    the oracle's lb_weights hook within binomial noise."""
    p = _payload(horizon=30.0)
    plan = compile_payload(p)
    eng = Engine(plan)
    n = 8
    st = eng.init_batch(scenario_keys(3, n))
    w = np.tile(np.asarray([[0.8, 0.2]]), (n, 1))
    st = eng.run_until(st, 30.0, weights=w)
    # srv-1's share of ARRIVALS: reconstruct via the edge gauges is heavy;
    # use the oracle for the reference split instead
    done_j = int(np.asarray(st.lat_count).sum())

    def oracle_split(seed):
        e = OracleEngine(p, seed=seed)
        e.start()
        e.lb_weights = {"lb-srv1": 0.8, "lb-srv2": 0.2}
        e.sim.run(until=30.0)
        s1 = e.edges["lb-srv1"].total_sent
        s2 = e.edges["lb-srv2"].total_sent
        return s1, s2, len(e.rqs_clock)

    s1 = s2 = done_o = 0
    for seed in range(n):
        a, b, d = oracle_split(seed)
        s1 += a
        s2 += b
        done_o += d
    frac_o = s1 / (s1 + s2)
    assert abs(frac_o - 0.8) < 0.02  # the hook itself honors the weights
    assert abs(done_j - done_o) / done_o < 0.05  # comparable traffic

    # jax engine split via latency asymmetry is indirect; check the direct
    # counter instead: lb_conn in-flight cannot reveal totals, so assert
    # via a one-sided experiment — all weight on slot 0 starves srv-2
    st0 = eng.init_batch(scenario_keys(5, 2))
    w0 = np.tile(np.asarray([[1.0, 0.0]]), (2, 1))
    st0 = eng.run_until(st0, 30.0, weights=w0)
    obs_env = BatchedLoadBalancerEnv(p, 2, seed=5)
    obs_env._state = st0
    core = np.asarray(obs_env._obs_fn(st0))
    srv2_residents = core[:, 7]
    assert np.all(srv2_residents == 0.0)


def test_batched_env_matches_sequential_env() -> None:
    """Uniform policy: batched rewards (event engine) agree with the
    sequential oracle env's on the same scenario family."""
    p = _payload(horizon=20.0)
    n = 12
    benv = BatchedLoadBalancerEnv(p, n, decision_period_s=1.0, seed=9)
    obs, _ = benv.reset()
    assert obs.shape == (n, benv.observation_dim)
    total = np.zeros(n)
    while True:
        obs, r, term, trunc, info = benv.step(np.ones((n, benv.action_dim)))
        assert obs.shape == (n, benv.observation_dim)
        assert r.shape == (n,)
        total += r
        if term.all():
            break
    senv = LoadBalancerEnv(p, decision_period_s=1.0)
    seq = []
    for seed in range(6):
        senv.reset(seed=seed)
        tot = 0.0
        while True:
            _, r, done, _, _ = senv.step(np.ones(2))
            tot += r
            if done:
                break
        seq.append(tot)
    assert abs(total.mean() - np.mean(seq)) / abs(np.mean(seq)) < 0.10


def test_batched_env_validation() -> None:
    p = _payload()
    env = BatchedLoadBalancerEnv(p, 2, seed=0)
    with pytest.raises(RuntimeError, match="reset"):
        env.step(np.ones((2, 2)))
    env.reset()
    with pytest.raises(ValueError, match="shape"):
        env.step(np.ones((3, 2)))
    with pytest.raises(ValueError, match="nonnegative"):
        env.step(np.full((2, 2), -1.0))
    single = yaml.safe_load(open("examples/yaml_input/data/single_server.yml"))
    with pytest.raises(ValueError, match="load-balancer"):
        BatchedLoadBalancerEnv(
            SimulationPayload.model_validate(single), 2,
        )


def test_reward_modes_batched() -> None:
    p = _payload()
    thr = BatchedLoadBalancerEnv(p, 2, reward="throughput", seed=0)
    thr.reset()
    _, r, _, _, info = thr.step(np.ones((2, 2)))
    assert np.allclose(r, info["window_completions"] / 1.0)

    custom = BatchedLoadBalancerEnv(
        p, 2, reward=lambda info: -info["window_arrivals"].astype(float), seed=0,
    )
    custom.reset()
    _, r2, _, _, info2 = custom.step(np.ones((2, 2)))
    assert np.allclose(r2, -info2["window_arrivals"])
