"""RL playground environment (reference roadmap milestone 6).

The env must be Gym-call-compatible, deterministic under seeding, route
according to the action weights, and terminate at the horizon.
"""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.rl import LoadBalancerEnv
from asyncflow_tpu.schemas.payload import SimulationPayload

LB = "examples/yaml_input/data/two_servers_lb.yml"


def _payload(horizon: int = 10) -> SimulationPayload:
    data = yaml.safe_load(open(LB).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    return SimulationPayload.model_validate(data)


@pytest.fixture()
def env() -> LoadBalancerEnv:
    return LoadBalancerEnv(_payload(), decision_period_s=1.0, seed=0)


def test_gym_call_shape(env: LoadBalancerEnv) -> None:
    obs, info = env.reset(seed=1)
    assert obs.shape == (env.observation_dim,)
    assert obs.dtype == np.float32
    first = True
    steps = 0
    while True:
        obs, r, terminated, truncated, info = env.step(np.ones(env.action_dim))
        steps += 1
        assert obs.shape == (env.observation_dim,)
        assert isinstance(r, float)
        if first:
            # the window features must be LIVE (the -3/-1 tail carries
            # completions / mean latency / arrivals of the last window)
            assert obs[-3] == info["window_completions"]
            assert obs[-1] == info["window_arrivals"]
            assert info["window_arrivals"] > 0
            first = False
        assert not truncated
        assert info["t"] == pytest.approx(min(steps * 1.0, env.horizon))
        if terminated:
            break
    assert steps == 10  # horizon / decision period


def test_seeded_determinism(env: LoadBalancerEnv) -> None:
    def rollout():
        env.reset(seed=7)
        rs = []
        while True:
            _, r, term, _, _ = env.step([0.7, 0.3])
            rs.append(r)
            if term:
                return rs

    assert rollout() == rollout()


def test_weights_route_traffic(env: LoadBalancerEnv) -> None:
    """All weight on slot 0 => the srv-2 routing edge CUMULATIVELY sends
    nothing, while srv-1's carries the whole load."""
    env.reset(seed=3)
    while True:
        _, _, term, _, _ = env.step([1.0, 0.0])
        if term:
            break
    eng = env._engine
    assert eng is not None
    assert eng.edges["lb-srv2"].total_sent == 0
    assert eng.edges["lb-srv1"].total_sent > 500


def test_zero_weights_fall_back_to_uniform(env: LoadBalancerEnv) -> None:
    env.reset(seed=5)
    _, _, _, _, info = env.step([0.0, 0.0])
    assert info["window_arrivals"] > 0  # traffic still flows


def test_reward_modes() -> None:
    p = _payload()
    thr = LoadBalancerEnv(p, reward="throughput", seed=0)
    thr.reset()
    _, r, _, _, info = thr.step([1.0, 1.0])
    assert r == pytest.approx(info["window_completions"] / 1.0)

    custom = LoadBalancerEnv(
        p, reward=lambda info: -float(len(info["window_latencies"])), seed=0,
    )
    custom.reset()
    _, r2, _, _, info2 = custom.step([1.0, 1.0])
    assert r2 == -float(len(info2["window_latencies"]))


def test_action_validation(env: LoadBalancerEnv) -> None:
    env.reset(seed=0)
    with pytest.raises(ValueError, match="shape"):
        env.step([1.0])
    with pytest.raises(ValueError, match="nonnegative"):
        env.step([1.0, -0.5])
    with pytest.raises(RuntimeError, match="reset"):
        LoadBalancerEnv(_payload()).step([1.0, 1.0])


def test_requires_load_balancer() -> None:
    data = yaml.safe_load(open("examples/yaml_input/data/single_server.yml").read())
    payload = SimulationPayload.model_validate(data)
    with pytest.raises(ValueError, match="load-balancer"):
        LoadBalancerEnv(payload)
