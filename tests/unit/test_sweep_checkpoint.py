"""Sweep checkpoint/resume: interrupted sweeps resume with identical results."""

import numpy as np
import pytest

from asyncflow_tpu.parallel import SweepRunner
from asyncflow_tpu.runtime.runner import SimulationRunner

pytestmark = pytest.mark.integration


def test_checkpoint_resume_identical(tmp_path) -> None:
    payload = SimulationRunner.from_yaml(
        "tests/integration/data/single_server.yml",
    ).simulation_input
    runner = SweepRunner(payload, use_mesh=False)

    # full uninterrupted run
    full = runner.run(12, seed=5, chunk_size=4)

    # checkpointed run persists one file per chunk
    ck = tmp_path / "ck"
    runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(ck))
    (run_dir,) = list(ck.iterdir())
    chunks = sorted(run_dir.glob("chunk_*.npz"))
    assert len(chunks) == 3

    # simulate a crash before the last chunk landed, then resume
    chunks[-1].unlink()
    resumed = runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(ck))

    np.testing.assert_array_equal(resumed.results.completed, full.results.completed)
    np.testing.assert_array_equal(
        resumed.results.latency_hist,
        full.results.latency_hist,
    )
    assert resumed.results.settings is not None  # survives the npz round trip
    # all three chunks persisted again
    assert len(sorted(run_dir.glob("chunk_*.npz"))) == 3


def test_checkpoint_keyed_by_overrides(tmp_path) -> None:
    """Chunks computed under different overrides must never be reused."""
    from asyncflow_tpu.parallel import make_overrides

    payload = SimulationRunner.from_yaml(
        "tests/integration/data/single_server.yml",
    ).simulation_input
    runner = SweepRunner(payload, use_mesh=False)
    ck = tmp_path / "ck"
    ov_a = make_overrides(runner.plan, 4, edge_mean_scale=np.full(4, 1.0))
    ov_b = make_overrides(runner.plan, 4, edge_mean_scale=np.full(4, 0.5))
    runner.run(4, seed=5, chunk_size=4, overrides=ov_a, checkpoint_dir=str(ck))
    rep_b = runner.run(4, seed=5, chunk_size=4, overrides=ov_b, checkpoint_dir=str(ck))
    # two distinct checkpoint dirs; B was actually computed (lower latencies)
    assert len(list(ck.iterdir())) == 2
    rep_a = runner.run(4, seed=5, chunk_size=4, overrides=ov_a, checkpoint_dir=str(ck))
    assert rep_b.aggregate_percentile(95) < rep_a.aggregate_percentile(95)


def test_checkpoint_resume_with_scanned_path(tmp_path) -> None:
    """Scanned fast path + checkpointing: interrupted and uninterrupted
    sweeps produce identical results (the scanned executable is reused
    across chunks including the padded tail)."""
    payload = SimulationRunner.from_yaml(
        "tests/integration/data/single_server.yml",
    ).simulation_input
    runner = SweepRunner(payload, use_mesh=False, scan_inner=4)
    full = runner.run(10, seed=3, chunk_size=8)

    ck = tmp_path / "ck"
    runner2 = SweepRunner(payload, use_mesh=False, scan_inner=4)
    first = runner2.run(10, seed=3, chunk_size=8, checkpoint_dir=str(ck))
    # resume from the persisted chunks (fresh runner, same grid)
    runner3 = SweepRunner(payload, use_mesh=False, scan_inner=4)
    resumed = runner3.run(10, seed=3, chunk_size=8, checkpoint_dir=str(ck))
    for a, b in ((first, full), (resumed, full)):
        np.testing.assert_array_equal(
            a.results.latency_hist, b.results.latency_hist,
        )
        np.testing.assert_array_equal(a.results.completed, b.results.completed)
