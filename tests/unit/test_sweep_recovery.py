"""Host-fault recovery: scenario quarantine, preemption-safe resume,
checkpoint integrity (digest sidecars, stale-tmp hygiene), transient-error
retry, and the ``kind="recovery"`` telemetry record
(docs/guides/fault-tolerance.md)."""

from __future__ import annotations

import json
import signal
import time

import numpy as np
import pytest
import yaml

from asyncflow_tpu.observability import TelemetryConfig, validate_run_record
from asyncflow_tpu.parallel.recovery import (
    PREEMPTED_EXIT_CODE,
    CorruptChunkError,
    QuarantineCapExceeded,
    RecoveryLog,
    RecoveryPolicy,
    SweepPreempted,
    is_transient,
    phase_watchdog,
    read_manifest,
)
from asyncflow_tpu.parallel.sweep import (
    SweepRunner,
    _SweepCheckpoint,
    make_overrides,
)
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"
HORIZON = 15


def _payload(horizon: int = HORIZON) -> SimulationPayload:
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    data["sim_settings"]["enabled_sample_metrics"] = []
    return SimulationPayload.model_validate(data)


def _nan_overrides(runner: SweepRunner, n: int, row: int):
    scale = np.ones(n)
    scale[row] = np.nan
    return make_overrides(runner.plan, n, edge_mean_scale=scale)


def _ones_overrides(runner: SweepRunner, n: int):
    return make_overrides(runner.plan, n, edge_mean_scale=np.ones(n))


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------


def test_backoff_is_capped_exponential() -> None:
    pol = RecoveryPolicy(backoff_base_s=1.0, backoff_cap_s=5.0)
    assert [pol.backoff(a) for a in range(5)] == [1.0, 2.0, 4.0, 5.0, 5.0]


def test_is_transient_classifier() -> None:
    assert is_transient(RuntimeError("UNAVAILABLE: socket closed"))
    assert is_transient(OSError("Connection reset by peer"))
    assert is_transient(RuntimeError("DEADLINE_EXCEEDED while waiting"))
    assert not is_transient(ValueError("shape mismatch"))
    # OOM has its own recovery (chunk downshift), never blind retry
    assert not is_transient(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))


def test_preempted_exit_code_is_distinct() -> None:
    # BSD EX_TEMPFAIL: resumable, not failed — and not a shell-builtin code
    assert PREEMPTED_EXIT_CODE == 75
    assert SweepPreempted("x").exit_code == PREEMPTED_EXIT_CODE


def test_phase_watchdog_records_named_diagnostic() -> None:
    log = RecoveryLog()
    with phase_watchdog("execute", 0.01, log=log, engine="fast", chunk=3):
        time.sleep(0.08)
    (action,) = [a for a in log.actions if a["action"] == "watchdog"]
    assert action["phase"] == "execute"
    assert action["engine"] == "fast"
    assert action["chunk"] == 3
    # an in-budget phase records nothing
    log2 = RecoveryLog()
    with phase_watchdog("execute", 5.0, log=log2):
        pass
    assert log2.actions == []


# ---------------------------------------------------------------------------
# scenario quarantine
# ---------------------------------------------------------------------------


def test_nan_scenario_quarantined_rest_bit_identical() -> None:
    """The acceptance bar: a 64-scenario sweep with one NaN-producing
    scenario completes with n_quarantined == 1 and the other 63 scenarios
    bit-identical to a clean sweep over the same keys."""
    payload = _payload()
    runner = SweepRunner(payload, engine="fast", use_mesh=False)
    n = 64
    report = runner.run(
        n, seed=7, overrides=_nan_overrides(runner, n, 17), chunk_size=16,
    )
    assert report.n_quarantined == 1
    assert report.quarantined_scenarios() == [17]
    assert "non-finite" in str(report.results.quarantine_reason[17])
    assert report.recovery is not None
    assert [a["scenario"] for a in report.recovery.actions] == [17]

    clean = runner.run(
        n, seed=7, overrides=_ones_overrides(runner, n), chunk_size=16,
    )
    keep = np.ones(n, bool)
    keep[17] = False
    for name in ("latency_hist", "latency_sum", "completed", "throughput",
                 "gauge_means", "total_generated"):
        np.testing.assert_array_equal(
            np.asarray(getattr(report.results, name))[keep],
            np.asarray(getattr(clean.results, name))[keep],
            err_msg=name,
        )
    # the masked row holds nothing: no pooled counts, no completions
    assert report.results.latency_hist[17].sum() == 0
    assert report.results.completed[17] == 0

    summary = report.summary()
    assert summary["n_quarantined"] == 1
    assert summary["effective_n_scenarios"] == n - 1
    assert summary["ci_excluded_scenarios"] == 1
    est = report.pooled_percentile_ci(95)
    assert est.n_excluded == 1
    assert np.isfinite(est.point)


def test_quarantine_parity_oracle_vs_jax() -> None:
    """Oracle (native C++ core) and JAX sweeps agree on WHICH scenario is
    quarantined.  The JAX arm hits a real NaN (closed-form fast path with
    a NaN edge mean); the float64 oracle core is numerically immune to
    that override, so its arm injects the equivalent non-finite metric at
    the chunk boundary for the same global scenario — the machinery under
    test (localize -> confirm by isolated re-run -> mask -> continue) is
    identical from there."""
    from asyncflow_tpu.engines.oracle.native import native_available

    payload = _payload()
    n, bad = 8, 3
    jax_runner = SweepRunner(payload, engine="fast", use_mesh=False)
    jax_rep = jax_runner.run(
        n, seed=11, overrides=_nan_overrides(jax_runner, n, bad), chunk_size=n,
    )
    assert jax_rep.quarantined_scenarios() == [bad]

    if not native_available():
        pytest.skip("native oracle core unavailable")
    native_runner = SweepRunner(payload, engine="native", use_mesh=False)
    real_run_chunk = native_runner.engine.run_chunk

    def poisoned_run_chunk(seed, first_global, count, ov, settings):
        part = real_run_chunk(seed, first_global, count, ov, settings)
        for row in range(count):
            if first_global + row == bad:
                part.latency_sum = np.array(part.latency_sum)
                part.latency_sum[row] = np.nan
        return part

    native_runner.engine.run_chunk = poisoned_run_chunk
    native_rep = native_runner.run(n, seed=11, chunk_size=n)
    assert native_rep.quarantined_scenarios() == jax_rep.quarantined_scenarios()
    assert native_rep.n_quarantined == 1


def test_quarantine_cap_aborts_on_systemic_failure(monkeypatch) -> None:
    """When every row is non-finite the problem is systemic: abort with
    the original diagnostic instead of masking the sweep away."""
    import asyncflow_tpu.parallel.sweep as sweep_mod

    payload = _payload()
    runner = SweepRunner(payload, engine="event", use_mesh=False)
    real = sweep_mod.sweep_results

    def poisoned(engine, final, settings=None, gauge_sel=None):
        part = real(engine, final, settings, gauge_sel=gauge_sel)
        part.latency_sum = np.full_like(np.array(part.latency_sum), np.nan)
        return part

    monkeypatch.setattr(sweep_mod, "sweep_results", poisoned)
    with pytest.raises(QuarantineCapExceeded, match="systemic"):
        runner.run(4, seed=0, chunk_size=4)


def test_quarantine_disabled_raises_like_before() -> None:
    payload = _payload()
    runner = SweepRunner(payload, engine="fast", use_mesh=False, recovery=None)
    with pytest.raises(ValueError, match="non-finite"):
        runner.run(8, seed=7, overrides=_nan_overrides(runner, 8, 3), chunk_size=8)


def test_quarantine_survives_checkpoint_resume(tmp_path) -> None:
    payload = _payload()
    runner = SweepRunner(payload, engine="fast", use_mesh=False)
    n = 16
    ov = _nan_overrides(runner, n, 5)
    first = runner.run(n, seed=3, overrides=ov, chunk_size=4,
                       checkpoint_dir=str(tmp_path))
    assert first.quarantined_scenarios() == [5]
    resumed = runner.run(n, seed=3, overrides=ov, chunk_size=4,
                         checkpoint_dir=str(tmp_path))
    # the mask and reason ride the chunk npz: a resumed run reports the
    # quarantine without re-running anything
    assert resumed.recovery is None  # nothing fired this run
    assert resumed.quarantined_scenarios() == [5]
    assert "non-finite" in str(resumed.results.quarantine_reason[5])
    np.testing.assert_array_equal(
        resumed.results.latency_hist, first.results.latency_hist,
    )


def test_bisect_isolates_deterministically_crashing_scenario() -> None:
    """A scenario that CRASHES the engine (no results at all) is bisected
    to — prefix-stable keys make sub-chunk re-runs bit-identical — and
    quarantined with the error as reason; every other row matches an
    undisturbed run."""
    from asyncflow_tpu.engines.jaxsim.engine import scenario_keys

    payload = _payload(horizon=12)
    n, bad = 8, 3
    baseline = SweepRunner(payload, engine="event", use_mesh=False).run(
        n, seed=9, chunk_size=n,
    )

    runner = SweepRunner(payload, engine="event", use_mesh=False)
    bad_key = np.asarray(scenario_keys(9, n))[bad]
    real_run_batch = runner.engine.run_batch

    def crashing_run_batch(keys, ov=None, **kw):
        keys_np = np.asarray(keys)
        if (keys_np == bad_key).all(axis=-1).any():
            msg = "INVALID_ARGUMENT: injected deterministic engine crash"
            raise RuntimeError(msg)
        return real_run_batch(keys, ov, **kw)

    runner.engine.run_batch = crashing_run_batch
    report = runner.run(n, seed=9, chunk_size=n)
    assert report.quarantined_scenarios() == [bad]
    reason = str(report.results.quarantine_reason[bad])
    assert "injected deterministic engine crash" in reason
    keep = np.ones(n, bool)
    keep[bad] = False
    np.testing.assert_array_equal(
        report.results.latency_hist[keep], baseline.results.latency_hist[keep],
    )
    np.testing.assert_array_equal(
        report.results.completed[keep], baseline.results.completed[keep],
    )


# ---------------------------------------------------------------------------
# transient-error retry
# ---------------------------------------------------------------------------

_FAST_RETRY = RecoveryPolicy(backoff_base_s=0.0, max_transient_retries=2)


def test_transient_error_retried_then_bit_identical() -> None:
    payload = _payload()
    baseline = SweepRunner(payload, engine="event", use_mesh=False).run(
        8, seed=9, chunk_size=8,
    )
    runner = SweepRunner(
        payload, engine="event", use_mesh=False, recovery=_FAST_RETRY,
    )
    real_run_batch = runner.engine.run_batch
    calls = {"n": 0}

    def flaky(keys, ov=None, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            msg = "UNAVAILABLE: socket closed (tunnel hiccup)"
            raise RuntimeError(msg)
        return real_run_batch(keys, ov, **kw)

    runner.engine.run_batch = flaky
    report = runner.run(8, seed=9, chunk_size=8)
    retries = [a for a in report.recovery.actions if a["action"] == "retry"]
    assert retries and "UNAVAILABLE" in retries[0]["error"]
    np.testing.assert_array_equal(
        report.results.latency_hist, baseline.results.latency_hist,
    )


def test_transient_retries_exhausted_reraises() -> None:
    payload = _payload()
    runner = SweepRunner(
        payload,
        engine="event",
        use_mesh=False,
        recovery=RecoveryPolicy(
            backoff_base_s=0.0, max_transient_retries=1, quarantine=False,
        ),
    )

    def always_down(keys, ov=None, **kw):
        raise RuntimeError("UNAVAILABLE: worker gone")

    runner.engine.run_batch = always_down
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
        runner.run(4, seed=0, chunk_size=4)


# ---------------------------------------------------------------------------
# preemption: SIGTERM drain + manifest + bit-identical resume
# ---------------------------------------------------------------------------


def test_sigterm_drain_manifest_and_resume_bit_identical(tmp_path) -> None:
    """Satellite acceptance: interrupt a checkpointed sweep after chunk k
    (simulated SIGTERM mid-run), resume, and the results are byte-identical
    to an uninterrupted run."""
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False)
    clean = runner.run(12, seed=5, chunk_size=4)

    ck = tmp_path / "ck"
    orig_save = _SweepCheckpoint.save
    calls = {"n": 0}

    def killing_save(self, start, part):
        orig_save(self, start, part)
        calls["n"] += 1
        if calls["n"] == 2:
            # delivered synchronously in the main thread: the drain handler
            # runs mid-sweep exactly as a real SIGTERM would land
            signal.raise_signal(signal.SIGTERM)

    _SweepCheckpoint.save = killing_save
    try:
        with pytest.raises(SweepPreempted) as excinfo:
            runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(ck))
    finally:
        _SweepCheckpoint.save = orig_save
    preempted = excinfo.value
    assert preempted.scenarios_done == 8
    assert preempted.signal_name == "SIGTERM"
    assert preempted.exit_code == PREEMPTED_EXIT_CODE
    (run_dir,) = list(ck.iterdir())
    manifest = read_manifest(run_dir)
    assert manifest is not None
    assert manifest["status"] == "preempted"
    assert manifest["scenarios_done"] == 8
    assert len(manifest["chunks"]) == 2

    resumed = runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(ck))
    np.testing.assert_array_equal(
        resumed.results.latency_hist, clean.results.latency_hist,
    )
    np.testing.assert_array_equal(
        resumed.results.completed, clean.results.completed,
    )
    assert read_manifest(run_dir)["status"] == "complete"


def test_preemption_without_checkpoint_still_distinct() -> None:
    """A drain signal mid-loop (work still undispatched, no checkpoint)
    raises the distinct exception; a signal landing once every chunk is
    already in the pipeline window simply drains to completion."""
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False)
    import asyncflow_tpu.parallel.sweep as sweep_mod

    real = sweep_mod.sweep_results
    fired = {"done": False}

    def signaling(engine, final, settings=None, gauge_sel=None):
        part = real(engine, final, settings, gauge_sel=gauge_sel)
        if not fired["done"]:
            fired["done"] = True
            signal.raise_signal(signal.SIGTERM)
        return part

    sweep_mod.sweep_results = signaling
    try:
        with pytest.raises(SweepPreempted) as excinfo:
            # 6 chunks vs the 3-chunk pipeline window: the first drained
            # fetch (which fires the signal) happens with chunks still
            # undispatched, so the loop must stop at the next boundary
            runner.run(24, seed=5, chunk_size=4)
    finally:
        sweep_mod.sweep_results = real
    assert excinfo.value.manifest_path is None
    assert "no checkpoint_dir" in str(excinfo.value)
    assert 0 < excinfo.value.scenarios_done < 24


# ---------------------------------------------------------------------------
# checkpoint integrity: corrupt chunks + digest sidecars + stale tmps
# ---------------------------------------------------------------------------


def _checkpointed_run(runner, tmp_path, n=12, seed=5, chunk=4):
    report = runner.run(n, seed=seed, chunk_size=chunk,
                        checkpoint_dir=str(tmp_path))
    (run_dir,) = [d for d in tmp_path.iterdir() if d.is_dir()]
    chunks = sorted(run_dir.glob("chunk_*.npz"))
    return report, run_dir, chunks


def test_truncated_chunk_discarded_and_recomputed(tmp_path) -> None:
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False)
    clean, run_dir, chunks = _checkpointed_run(runner, tmp_path)
    blob = chunks[1].read_bytes()
    chunks[1].write_bytes(blob[: len(blob) // 2])  # killed mid-write

    with pytest.warns(UserWarning, match="digest|corrupt"):
        resumed = runner.run(12, seed=5, chunk_size=4,
                             checkpoint_dir=str(tmp_path))
    np.testing.assert_array_equal(
        resumed.results.latency_hist, clean.results.latency_hist,
    )
    actions = [a["action"] for a in resumed.recovery.actions]
    assert "discard_chunk" in actions
    # the recomputed chunk is back on disk and intact
    assert len(sorted(run_dir.glob("chunk_*.npz"))) == 3


def test_corrupt_chunk_raises_named_diagnostic(tmp_path) -> None:
    """Satellite: a corrupt npz surfaces as CorruptChunkError naming the
    file and the remedy — never a bare zipfile.BadZipFile."""
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False, recovery=None)
    _, run_dir, chunks = _checkpointed_run(runner, tmp_path)
    chunks[0].write_bytes(b"not an npz at all")
    with pytest.raises(CorruptChunkError) as excinfo:
        runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(tmp_path))
    msg = str(excinfo.value)
    assert chunks[0].name in msg
    assert "recompute" in msg


def test_digest_sidecar_catches_silent_bitflip(tmp_path) -> None:
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False, recovery=None)
    _, run_dir, chunks = _checkpointed_run(runner, tmp_path)
    blob = bytearray(chunks[1].read_bytes())
    blob[len(blob) // 2] ^= 0xFF  # flip one payload byte, keep the length
    chunks[1].write_bytes(bytes(blob))
    with pytest.raises(CorruptChunkError, match="digest"):
        runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(tmp_path))


def test_stale_tmps_swept_on_open(tmp_path) -> None:
    """Satellite: tmp files leaked by killed runs are removed when the
    checkpoint store opens (the atomic-rename path leaks them when the
    process dies mid-np.savez)."""
    payload = _payload()
    runner = SweepRunner(payload, use_mesh=False)
    _, run_dir, _ = _checkpointed_run(runner, tmp_path)
    stale = run_dir / ".chunk_00000000.99999.tmp.npz"
    stale.write_bytes(b"leaked by a killed run")
    report = runner.run(12, seed=5, chunk_size=4, checkpoint_dir=str(tmp_path))
    assert not stale.exists()
    (clean_action,) = [
        a for a in report.recovery.actions if a["action"] == "clean_tmp"
    ]
    assert stale.name in clean_action["files"]


# ---------------------------------------------------------------------------
# telemetry: the kind="recovery" run record
# ---------------------------------------------------------------------------


def test_recovery_telemetry_record(tmp_path) -> None:
    payload = _payload()
    out = tmp_path / "runs.jsonl"
    runner = SweepRunner(
        payload,
        engine="fast",
        use_mesh=False,
        telemetry=TelemetryConfig(
            jsonl_path=out, ledger_path=tmp_path / "ledger.jsonl",
        ),
    )
    n = 8
    runner.run(n, seed=7, overrides=_nan_overrides(runner, n, 2), chunk_size=n)
    records = [json.loads(line) for line in out.read_text().splitlines()]
    kinds = [r["kind"] for r in records]
    assert "recovery" in kinds
    assert "sweep" in kinds
    (rec,) = [r for r in records if r["kind"] == "recovery"]
    assert validate_run_record(rec) == []
    assert rec["meta"]["n_quarantined"] == 1
    assert rec["meta"]["actions"][0]["action"] == "quarantine"
    assert rec["meta"]["actions"][0]["scenario"] == 2
    (sweep_rec,) = [r for r in records if r["kind"] == "sweep"]
    assert sweep_rec["meta"]["n_quarantined"] == 1


# ---------------------------------------------------------------------------
# estimators: effective-n and noted exclusions
# ---------------------------------------------------------------------------


def test_estimators_report_effective_n() -> None:
    from asyncflow_tpu.analysis import (
        effective_results,
        interval_for_metric,
        paired_delta_for_metric,
    )

    payload = _payload()
    runner = SweepRunner(payload, engine="fast", use_mesh=False)
    n = 16
    rep = runner.run(
        n, seed=7, overrides=_nan_overrides(runner, n, 4), chunk_size=n,
    )
    eff, n_excluded = effective_results(rep.results)
    assert n_excluded == 1
    assert np.asarray(eff.completed).shape[0] == n - 1

    est = interval_for_metric(rep.results, "latency_p95_s")
    assert est.n_excluded == 1
    assert est.as_dict()["n_excluded"] == 1
    goodput = interval_for_metric(rep.results, "goodput_fraction", n_boot=64)
    assert goodput.n_excluded == 1
    assert np.isfinite(goodput.point)

    clean = runner.run(
        n, seed=7, overrides=_ones_overrides(runner, n), chunk_size=n,
    )
    delta = paired_delta_for_metric(
        rep.results, clean.results, "latency_p95_s", n_boot=64,
    )
    assert delta.n_excluded == 1
