"""Sweep-layer resilience: fault-timing Monte-Carlo through SweepRunner,
OOM chunk downshift, the finite-results guard, and plan-aware checkpoint
identity."""

from __future__ import annotations

import numpy as np
import pytest
import yaml

from asyncflow_tpu.parallel.sweep import (
    SweepRunner,
    _check_finite,
    _is_oom,
    make_overrides,
)
from asyncflow_tpu.schemas.payload import SimulationPayload

BASE = "tests/integration/data/single_server.yml"
HORIZON = 40


def _payload(mut=None, horizon: int = HORIZON) -> SimulationPayload:
    data = yaml.safe_load(open(BASE).read())
    data["sim_settings"]["total_simulation_time"] = horizon
    data["sim_settings"]["enabled_sample_metrics"] = []
    if mut:
        mut(data)
    return SimulationPayload.model_validate(data)


def _resilient(data) -> None:
    data["retry_policy"] = {
        "request_timeout_s": 0.5,
        "max_attempts": 3,
        "backoff_base_s": 0.05,
        "backoff_multiplier": 2.0,
        "backoff_cap_s": 0.5,
        "budget_tokens": 40,
        "budget_refill_per_s": 2.0,
    }
    data["fault_timeline"] = {
        "events": [
            {
                "fault_id": "crash",
                "kind": "server_outage",
                "target_id": "srv-1",
                "t_start": 10.0,
                "t_end": 20.0,
            },
        ],
    }


def test_fault_sweep_end_to_end_and_deterministic() -> None:
    """A fault-timing sweep auto-routes to the scan fast path (round-8
    fence burn-down), produces the per-scenario resilience counters, and
    is deterministic under a fixed seed."""
    payload = _payload(_resilient)
    runner = SweepRunner(payload, engine="auto", use_mesh=False)
    assert runner.engine_kind == "fast"
    n = 8
    shifts = np.linspace(0.0, 15.0, n)
    ov = make_overrides(
        runner.plan, n, fault_shift=shifts, retry_timeout=np.full(n, 0.5),
    )
    rep1 = runner.run(n, seed=5, overrides=ov, chunk_size=4)
    rep2 = runner.run(n, seed=5, overrides=ov, chunk_size=4)
    res = rep1.results
    assert res.total_timed_out is not None
    assert res.total_retries is not None
    assert res.retry_budget_exhausted is not None
    assert res.attempts_hist is not None
    assert res.attempts_hist.shape == (n, 3)
    assert int(res.total_rejected.sum()) > 0  # the outage bites
    for name in (
        "completed",
        "total_generated",
        "total_rejected",
        "total_timed_out",
        "total_retries",
        "attempts_hist",
    ):
        assert np.array_equal(
            getattr(rep1.results, name), getattr(rep2.results, name),
        ), name
    summary = rep1.summary()
    assert summary["retries_total"] == int(res.total_retries.sum())
    assert 0.0 < summary["goodput_fraction"] <= 1.0


def test_resilient_plans_refuse_native_and_pallas() -> None:
    payload = _payload(_resilient)
    for engine in ("native", "pallas"):
        with pytest.raises(ValueError, match="does not model"):
            SweepRunner(payload, engine=engine, use_mesh=False)


def test_scan_inner_decided_once_after_routing() -> None:
    """``scan_inner`` is a fast-path-only knob, decided AFTER the engine
    is known: the native C++ core never scans (the old code path defaulted
    ``_scan_inner`` before routing, leaving a stale value on non-fast
    engines), and the event engine dispatches on 0 too."""
    if _native_available():
        native = SweepRunner(
            _payload(), engine="native", use_mesh=False, scan_inner=8,
        )
        assert native.engine_kind == "native"
        assert native._scan_inner == 0
    event = SweepRunner(
        _payload(), engine="event", use_mesh=False, scan_inner=8,
    )
    assert event.engine_kind == "event"
    assert event._scan_inner == 0
    fast_default = SweepRunner(_payload(), engine="fast", use_mesh=False)
    assert fast_default.engine_kind == "fast"
    assert fast_default._scan_inner == 16
    fast_explicit = SweepRunner(
        _payload(), engine="fast", use_mesh=False, scan_inner=4,
    )
    assert fast_explicit._scan_inner == 4


def _native_available() -> bool:
    from asyncflow_tpu.engines.oracle.native import native_available

    return native_available()


def test_fault_overrides_need_fault_plan() -> None:
    runner = SweepRunner(_payload(), engine="auto", use_mesh=False)
    with pytest.raises(ValueError, match="fault_timeline"):
        make_overrides(runner.plan, 4, fault_shift=np.zeros(4))
    with pytest.raises(ValueError, match="retry_policy"):
        make_overrides(runner.plan, 4, retry_timeout=np.full(4, 0.5))


# ---------------------------------------------------------------------------
# graceful degradation: OOM -> chunk downshift
# ---------------------------------------------------------------------------


class _FakeOOM(RuntimeError):
    pass


def test_is_oom_classifier() -> None:
    assert _is_oom(_FakeOOM("RESOURCE_EXHAUSTED: Out of memory on TPU"))
    assert _is_oom(RuntimeError("Allocator ran out of memory"))
    assert not _is_oom(ValueError("shape mismatch"))


def test_sweep_survives_injected_oom_with_downshift(monkeypatch) -> None:
    """An injected RESOURCE_EXHAUSTED on the first chunk halves the chunk,
    re-runs it, and the sweep's results are identical to an undisturbed
    run (the scenario key grid is position-stable under chunking)."""
    payload = _payload(_resilient)
    runner = SweepRunner(payload, engine="auto", use_mesh=False)
    n = 8
    baseline = runner.run(n, seed=9, chunk_size=8)

    runner2 = SweepRunner(payload, engine="auto", use_mesh=False)
    # auto routes this resilient plan to the scan fast path (round 8),
    # whose sweeps dispatch through run_batch_scanned when scan_inner > 0
    target = "run_batch_scanned" if runner2._scan_inner else "run_batch"
    real_run_batch = getattr(runner2.engine, target)
    calls = {"n": 0}

    def flaky_run_batch(keys, ov=None, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            msg = "RESOURCE_EXHAUSTED: out of memory allocating 1.0GiB"
            raise _FakeOOM(msg)
        return real_run_batch(keys, ov, **kw)

    monkeypatch.setattr(runner2.engine, target, flaky_run_batch)
    report = runner2.run(n, seed=9, chunk_size=8)
    assert report.downshifts == [{"scenario_start": 0, "from": 8, "to": 4}]
    assert np.array_equal(report.results.completed, baseline.results.completed)
    assert np.array_equal(
        report.results.latency_hist, baseline.results.latency_hist,
    )


def test_sweep_oom_at_floor_reraises_with_hint(monkeypatch) -> None:
    payload = _payload()
    runner = SweepRunner(payload, engine="event", use_mesh=False)

    def always_oom(keys, ov=None, **kw):
        raise _FakeOOM("RESOURCE_EXHAUSTED: out of memory")

    monkeypatch.setattr(runner.engine, "run_batch", always_oom)
    with pytest.raises(RuntimeError, match="minimum chunk size"):
        runner.run(4, seed=0, chunk_size=2)


# ---------------------------------------------------------------------------
# finite-results guard
# ---------------------------------------------------------------------------


def test_check_finite_names_engine_chunk_and_metric() -> None:
    payload = _payload()
    runner = SweepRunner(payload, engine="event", use_mesh=False)
    report = runner.run(2, seed=0, chunk_size=2)
    part = report.results
    _check_finite(part, "event", 0, 0)  # clean results pass
    import dataclasses

    bad = dataclasses.replace(
        part, latency_sum=np.array([np.nan, 1.0]),
    )
    with pytest.raises(ValueError, match="event.*chunk 3.*latency_sum"):
        _check_finite(bad, "event", 3, 128)
    # +inf latency_min on a zero-completion scenario is LEGAL
    empty_min = dataclasses.replace(
        part,
        latency_min=np.array([np.inf, 0.01]),
        completed=np.array([0, 5]),
    )
    _check_finite(empty_min, "event", 0, 0)


def test_sweep_raises_on_nonfinite_chunk(monkeypatch) -> None:
    payload = _payload()
    runner = SweepRunner(payload, engine="event", use_mesh=False)
    import asyncflow_tpu.parallel.sweep as sweep_mod

    real = sweep_mod.sweep_results

    def poisoned(engine, final, settings=None, gauge_sel=None):
        part = real(engine, final, settings, gauge_sel=gauge_sel)
        part.latency_sum = np.full_like(part.latency_sum, np.nan)
        return part

    monkeypatch.setattr(sweep_mod, "sweep_results", poisoned)
    with pytest.raises(ValueError, match="non-finite.*latency_sum"):
        runner.run(2, seed=0, chunk_size=2)


# ---------------------------------------------------------------------------
# checkpoint identity incorporates the lowered plan
# ---------------------------------------------------------------------------


def test_checkpoint_identity_tracks_fault_timing(tmp_path) -> None:
    """Resuming a checkpoint against a changed fault timeline must land in
    a DIFFERENT checkpoint directory (no silent splicing)."""

    def at(t0):
        def mut(data):
            _resilient(data)
            data["fault_timeline"]["events"][0]["t_start"] = t0
            data["fault_timeline"]["events"][0]["t_end"] = t0 + 10.0

        return mut

    r1 = SweepRunner(_payload(at(5.0)), engine="event", use_mesh=False)
    r2 = SweepRunner(_payload(at(12.0)), engine="event", use_mesh=False)
    assert r1._checkpoint_identity(None) != r2._checkpoint_identity(None)
    # identical scenarios agree (checkpoints remain shareable)
    r1b = SweepRunner(_payload(at(5.0)), engine="event", use_mesh=False)
    assert r1._checkpoint_identity(None) == r1b._checkpoint_identity(None)

    # and a checkpointed resilient sweep resumes cleanly from disk
    runner = SweepRunner(_payload(_resilient), engine="event", use_mesh=False)
    rep = runner.run(4, seed=2, chunk_size=2, checkpoint_dir=str(tmp_path))
    resumed = runner.run(4, seed=2, chunk_size=2, checkpoint_dir=str(tmp_path))
    assert np.array_equal(rep.results.completed, resumed.results.completed)
    assert np.array_equal(
        rep.results.total_retries, resumed.results.total_retries,
    )
    assert np.array_equal(
        rep.results.attempts_hist, resumed.results.attempts_hist,
    )
