"""Utility smoke tests."""

import time

from asyncflow_tpu.utils import Stopwatch


def test_stopwatch_sections() -> None:
    watch = Stopwatch()
    with watch.section("a"):
        time.sleep(0.01)
    with watch.section("b"):
        pass
    assert watch.sections["a"] >= 0.01
    assert "a" in watch.report()
